"""Auto-resume supervisor: crash recovery with zero operator action.

The reference delegates failure recovery to Flink's restart strategies
(SURVEY §5); here a parent process respawns the job and the child
resumes from its checkpoint. The headline property (VERDICT r2, Next
#7): SIGKILL the job under the supervisor and the total stdout is
byte-identical to an uninterrupted run."""

import os
import subprocess
import sys

import pytest

from tpu_cooccurrence.supervisor import child_argv, supervise

from test_cli import write_stream

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ENV = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")


def test_child_argv_strips_supervisor_flags():
    argv = ["-i", "x.csv", "--restart-on-failure", "3", "-ws", "10",
            "--restart-delay-ms=0", "--restart-on-failure=2"]
    assert child_argv(argv) == ["-i", "x.csv", "-ws", "10"]


class _Sink:
    def __init__(self):
        self.text = ""

    def write(self, s):
        self.text += s


def test_supervise_retries_then_succeeds(tmp_path):
    """Two failing attempts (partial output discarded), then success:
    rc 0 and ONLY the successful attempt's stdout comes through."""
    marker = tmp_path / "attempts"
    code = (
        "import os, sys\n"
        f"p = {str(marker)!r}\n"
        "n = int(open(p).read()) if os.path.exists(p) else 0\n"
        "open(p, 'w').write(str(n + 1))\n"
        "if n < 2:\n"
        "    print('partial garbage', flush=True)\n"
        "    sys.exit(3)\n"
        "print('final output')\n"
    )
    sink = _Sink()
    rc = supervise([sys.executable, "-c", code], attempts=2, delay_s=0,
                   stdout=sink)
    assert rc == 0
    assert sink.text == "final output\n"
    assert marker.read_text() == "3"


def test_supervise_exhausts_attempts(tmp_path):
    sink = _Sink()
    rc = supervise([sys.executable, "-c", "import sys; sys.exit(7)"],
                   attempts=2, delay_s=0, stdout=sink)
    assert rc == 7
    assert sink.text == ""


def test_supervise_timeout_counts_as_failed_attempt(tmp_path):
    """A hung attempt (timeout_s) is a failed attempt, not a supervisor
    crash: the child is killed, the retry runs, output comes through."""
    marker = tmp_path / "ran-once"
    code = (
        "import os, sys, time\n"
        f"p = {str(marker)!r}\n"
        "if not os.path.exists(p):\n"
        "    open(p, 'w').close()\n"
        "    time.sleep(600)\n"
        "print('after hang')\n"
    )
    sink = _Sink()
    rc = supervise([sys.executable, "-c", code], attempts=1, delay_s=0,
                   stdout=sink, timeout_s=3)
    assert rc == 0
    assert sink.text == "after hang\n"
    sink2 = _Sink()
    rc = supervise([sys.executable, "-c", "import time; time.sleep(600)"],
                   attempts=0, delay_s=0, stdout=sink2, timeout_s=1)
    assert rc == 124  # exhausted: timeout's conventional exit code
    assert sink2.text == ""


def test_restart_flag_abbreviation_rejected():
    """allow_abbrev=False: `--restart-on` must NOT parse as
    --restart-on-failure (an abbreviation would survive child_argv's
    exact-name strip and nest supervisors indefinitely)."""
    import pytest

    from tpu_cooccurrence.config import Config

    with pytest.raises(SystemExit):
        Config.from_args(["-i", "x.csv", "-ws", "10", "--restart-on", "2"])


def test_restart_rejected_with_process_continuously():
    import pytest

    from tpu_cooccurrence.config import Config

    with pytest.raises(ValueError, match="process-continuously"):
        Config.from_args(["-i", "x.csv", "-ws", "10",
                          "--restart-on-failure", "2",
                          "--process-continuously"])


def test_restart_rejected_with_multihost():
    """A respawned child re-joining the coordinator while surviving peers
    are blocked mid-collective would hang the distributed run; supervise
    multi-host jobs externally instead."""
    import pytest

    from tpu_cooccurrence.config import Config

    with pytest.raises(ValueError, match="multi-host"):
        Config.from_args(["-i", "x.csv", "-ws", "10",
                          "--restart-on-failure", "2",
                          "--coordinator", "127.0.0.1:9999",
                          "--num-processes", "2", "--process-id", "0"])


@pytest.mark.slow
def test_supervise_large_output_spools_to_disk(tmp_path):
    """A multi-hundred-MB child stream must not live in supervisor RAM:
    stdout spools to disk per attempt (VERDICT r3, Weak #3). Output
    integrity is checked end-to-end; RSS growth is bounded well under
    the stream size."""
    import resource

    n_mb = 256
    line = "x" * 1023  # 1 KB with newline
    code = (f"import sys\n"
            f"for _ in range({n_mb * 1024}):\n"
            f"    sys.stdout.write({line!r} + '\\n')\n")
    out_path = tmp_path / "out.txt"
    rss_before = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    with open(out_path, "w") as sink:  # has .buffer → binary fast path
        rc = supervise([sys.executable, "-c", code], attempts=0, delay_s=0,
                       stdout=sink)
    rss_after = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    assert rc == 0
    assert out_path.stat().st_size == n_mb * 1024 * 1024
    with open(out_path) as f:
        first = f.readline()
    assert first == line + "\n"
    # ru_maxrss is KB on Linux; allow 64 MB of slack for the interpreter,
    # far under the 256 MB stream a PIPE buffer would have held.
    assert rss_after - rss_before < 64 * 1024, (
        f"supervisor RSS grew {(rss_after - rss_before) // 1024} MB "
        f"on a {n_mb} MB stream — stdout is being buffered in memory")


def test_supervise_text_sink_multibyte_across_chunks():
    """Text sinks decode incrementally; multi-byte UTF-8 sequences that
    straddle copy-chunk boundaries must survive."""
    # 3-byte chars at 1-byte offset guarantee straddles at any power-of-2
    # chunk size.
    code = ("import sys\n"
            "sys.stdout.write('a' + '\\u20ac' * 100000)\n"
            "sys.stdout.write('x\\r\\ny')\n")
    sink = _Sink()
    rc = supervise([sys.executable, "-c", code], attempts=0, delay_s=0,
                   stdout=sink)
    assert rc == 0
    # \r\n must come through untranslated (byte-identical contract).
    assert sink.text == "a" + "\u20ac" * 100000 + "x\r\ny"


def test_supervisor_quotes_dead_childs_journal_tail(tmp_path, caplog):
    """A SIGKILLed child's journal survives (including a torn final
    line) and the supervisor's restart log quotes its tail — the crashed
    attempt's last fired windows are not lost with its discarded stdout."""
    import logging

    jpath = tmp_path / "j.jsonl"
    marker = tmp_path / "crashed-once"
    code = (
        "import os, signal, sys\n"
        "sys.path.insert(0, sys.argv[3])\n"
        "from tpu_cooccurrence.observability.journal import RunJournal, VERSION\n"
        "rec = dict(v=VERSION, seq=1, ts=100, events=5, pairs=3,\n"
        "           rows_scored=2, sample_seconds=0.01, score_seconds=0.02,\n"
        "           ring_depth=0, stall_seconds=0.0, wall_unix=1.0,\n"
        "           counters={}, wire={})\n"
        "j = RunJournal(sys.argv[1])\n"
        "if not os.path.exists(sys.argv[2]):\n"
        "    open(sys.argv[2], 'w').close()\n"
        "    j.record(rec)\n"
        "    j.record(dict(rec, seq=2, ts=200))\n"
        "    j._f.write('{\"v\": 1, \"seq\": 3, \"ts\"')  # torn mid-write\n"
        "    j._f.flush()\n"
        "    os.kill(os.getpid(), signal.SIGKILL)\n"
        "j.record(dict(rec, seq=3, ts=300))\n"
        "print('done')\n"
    )
    sink = _Sink()
    with caplog.at_level(logging.WARNING, "tpu_cooccurrence.supervisor"):
        rc = supervise([sys.executable, "-c", code, str(jpath), str(marker),
                        REPO],
                       attempts=1, delay_s=0, stdout=sink,
                       journal_path=str(jpath))
    assert rc == 0 and sink.text == "done\n"
    quoted = [r.message for r in caplog.records if "journal" in r.message]
    assert any("journal tail (2 record(s)" in m for m in quoted), quoted
    # The dead attempt's LAST fired window (seq 2, not the torn seq-3
    # line) is quoted verbatim.
    assert any('"seq": 2' in m and '"ts": 200' in m for m in quoted), quoted
    # The file itself carries both attempts: crash tail + clean rerun.
    from tpu_cooccurrence.observability.journal import read_records

    assert [r["seq"] for r in read_records(str(jpath))] == [1, 2, 3]


def test_supervisor_journal_tail_missing_file_logs_and_continues(tmp_path,
                                                                 caplog):
    import logging

    sink = _Sink()
    with caplog.at_level(logging.WARNING, "tpu_cooccurrence.supervisor"):
        rc = supervise([sys.executable, "-c", "import sys; sys.exit(3)"],
                       attempts=0, delay_s=0, stdout=sink,
                       journal_path=str(tmp_path / "never-written.jsonl"))
    assert rc == 3
    assert any("wrote no journal records" in r.message
               for r in caplog.records)


def test_supervisor_does_not_quote_stale_journal_as_dead_childs(tmp_path,
                                                                caplog):
    """A child that dies before its first window (startup crash) must not
    have an earlier run's journal records quoted as its last act — even
    when opening the journal grew the file by sealing a predecessor's
    torn line (the 1-byte write that defeats a size-only guard)."""
    import logging

    jpath = tmp_path / "j.jsonl"
    # Earlier run's record plus a torn final line (no trailing newline):
    # the child's RunJournal open seals it with "\n" before crashing.
    jpath.write_text('{"v": 1, "seq": 9, "ts": 900}\n{"v": 1, "seq": 10')
    code = ("import sys\n"
            "sys.path.insert(0, sys.argv[2])\n"
            "from tpu_cooccurrence.observability.journal import RunJournal\n"
            "RunJournal(sys.argv[1])\n"
            "sys.exit(5)\n")
    sink = _Sink()
    with caplog.at_level(logging.WARNING, "tpu_cooccurrence.supervisor"):
        rc = supervise([sys.executable, "-c", code, str(jpath), REPO],
                       attempts=0, delay_s=0, stdout=sink,
                       journal_path=str(jpath))
    assert rc == 5
    msgs = [r.message for r in caplog.records]
    assert any("wrote no journal records" in m for m in msgs), msgs
    assert not any('"seq": 9' in m for m in msgs), msgs


@pytest.mark.slow
def test_sigkill_under_supervisor_output_identical(tmp_path):
    """SIGKILL mid-run (right after the first periodic checkpoint lands);
    the supervisor restarts, the child restores, and total stdout is
    byte-identical to an uninterrupted run — zero operator action. The
    run journal survives the kill: every record validates and the
    supervisor quotes the dead attempt's tail."""
    f = tmp_path / "in.csv"
    write_stream(f, n=60_000)
    jpath = tmp_path / "journal.jsonl"
    cli_args = ["-i", str(f), "-ws", "20", "-ic", "8", "-uc", "5",
                "-s", "0xC0FFEE", "--backend", "oracle",
                "--checkpoint-every-windows", "5"]

    clean = subprocess.run(
        [sys.executable, "-m", "tpu_cooccurrence.cli"] + cli_args
        + ["--checkpoint-dir", str(tmp_path / "ck-clean")],
        capture_output=True, text=True, env=ENV, cwd=REPO, timeout=300)
    assert clean.returncode == 0, clean.stderr[-800:]

    ck = tmp_path / "ck"
    worker = os.path.join(REPO, "tests", "supervised_crash_worker.py")
    cmd = [sys.executable, worker, str(ck), str(tmp_path / "crashed-once")]
    cmd += cli_args + ["--checkpoint-dir", str(ck), "--journal", str(jpath)]
    sink = _Sink()
    rc = supervise(cmd, attempts=2, delay_s=0, stdout=sink,
                   journal_path=str(jpath))
    assert rc == 0
    assert (tmp_path / "crashed-once").exists(), "crash never injected"
    assert sink.text == clean.stdout
    # Journal integrity across the kill + restore: every surviving line
    # validates, and the stream replay is deterministic — any window
    # ordinal journaled by both attempts carries identical logical fields.
    from tpu_cooccurrence.observability.journal import (read_records,
                                                        validate_record)

    recs = list(read_records(str(jpath)))
    assert recs, "journal never written"
    by_seq = {}
    for r in recs:
        validate_record(r)
        logical = (r["ts"], r["events"], r["pairs"])
        assert by_seq.setdefault(r["seq"], logical) == logical
    assert max(by_seq) == len(by_seq), "window ordinals must be gapless"


def test_cli_restart_flag_healthy_run(tmp_path, capsys):
    """--restart-on-failure on a healthy run: supervised child executes
    once and the output matches an unsupervised run."""
    f = tmp_path / "in.csv"
    write_stream(f)
    base = ["-i", str(f), "-ws", "50", "--backend", "oracle",
            "-s", "0xC0FFEE"]
    plain = subprocess.run(
        [sys.executable, "-m", "tpu_cooccurrence.cli"] + base,
        capture_output=True, text=True, env=ENV, cwd=REPO, timeout=300)
    assert plain.returncode == 0, plain.stderr[-800:]
    supervised = subprocess.run(
        [sys.executable, "-m", "tpu_cooccurrence.cli"] + base
        + ["--restart-on-failure", "2", "--restart-delay-ms", "0"],
        capture_output=True, text=True, env=ENV, cwd=REPO, timeout=300)
    assert supervised.returncode == 0, supervised.stderr[-800:]
    assert supervised.stdout == plain.stdout
