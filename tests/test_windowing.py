"""Window assigner + engine unit tests."""

import numpy as np
import pytest

from tpu_cooccurrence.windowing.assigners import SlidingWindows, TumblingWindows
from tpu_cooccurrence.windowing.engine import WindowEngine


def test_tumbling_assignment():
    w = TumblingWindows(10)
    np.testing.assert_array_equal(
        w.assign(np.array([0, 9, 10, 19, 25])), [0, 0, 10, 10, 20])
    assert w.max_timestamp(10) == 19
    assert w.assign_scalar(15) == [10]


def test_sliding_assignment_scalar():
    w = SlidingWindows(10, 5)
    # ts=12 is inside [10,20) and [5,15).
    assert sorted(w.assign_scalar(12)) == [5, 10]
    # ts=3 inside [0,10) and [-5,5).
    assert sorted(w.assign_scalar(3)) == [-5, 0]


def test_sliding_assignment_vectorized_matches_scalar():
    w = SlidingWindows(12, 4)
    ts = np.arange(-40, 40)   # spans pre-epoch: floors must agree too
    batch = w.assign(ts)
    assert batch.shape == (80, 3)
    for pos, t in enumerate(ts.tolist()):
        assert sorted(batch[pos].tolist()) == sorted(w.assign_scalar(t))


def test_sliding_requires_divisible():
    with pytest.raises(ValueError):
        SlidingWindows(10, 3)


def test_negative_timestamps_assign_floored():
    """Event time is a raw long in the reference (pre-epoch timestamps
    are legal CSV input); window starts must floor toward -inf, not
    truncate toward zero — Python/numpy // both floor, matching
    Flink's TimeWindow.getWindowStartWithOffset."""
    w = TumblingWindows(10)
    np.testing.assert_array_equal(
        w.assign(np.array([-1, -10, -11, -25, 0])),
        [-10, -10, -20, -30, 0])
    assert w.assign_scalar(-1) == [-10]
    assert w.max_timestamp(-10) == -1
    s = SlidingWindows(10, 5)
    # ts=-3 is inside [-5,5) and [-10,0). (The batch-vs-scalar sweep
    # over negatives lives in
    # test_sliding_assignment_vectorized_matches_scalar.)
    assert sorted(s.assign_scalar(-3)) == [-10, -5]


def test_engine_fires_in_order_and_drops_late():
    eng = WindowEngine(10)
    users = np.array([1, 2, 3, 4], dtype=np.int64)
    items = np.array([10, 20, 30, 40], dtype=np.int64)
    ts = np.array([5, 25, 7, 15], dtype=np.int64)  # 7 and 15 late (wm=24)
    n_late = eng.add_batch(users, items, ts)
    assert n_late == 2
    fired = list(eng.fire_ready())
    # Windows [0,10) and [10,20) complete at wm=24, but [10,20) got no
    # surviving elements; only [0,10) fires. [20,30) still open.
    assert [f[0] for f in fired] == [9]
    np.testing.assert_array_equal(fired[0][2], [10])  # item 10 in w0
    fired_final = list(eng.fire_ready(final=True))
    assert [f[0] for f in fired_final] == [29]
    np.testing.assert_array_equal(fired_final[0][2], [20])


def test_engine_equal_timestamps_kept():
    eng = WindowEngine(10)
    n_late = eng.add_batch(
        np.array([1, 2]), np.array([10, 20]), np.array([5, 5], dtype=np.int64))
    assert n_late == 0


def test_engine_preserves_arrival_order_within_window():
    eng = WindowEngine(100)
    eng.add_batch(np.array([1, 1]), np.array([10, 20]),
                  np.array([5, 6], dtype=np.int64))
    eng.add_batch(np.array([1]), np.array([30]), np.array([7], dtype=np.int64))
    (ts, users, items), = list(eng.fire_ready(final=True))
    np.testing.assert_array_equal(items, [10, 20, 30])
