"""Unattended grant watcher (VERDICT r3, Next #1).

The loop logic runs against stub probe/stage subprocesses — the real
probe code path (subprocess + hard timeout + GRANT- marker parse) is
exercised as-is; only the code string the probe child runs is swapped,
so a dead tunnel can be simulated without jax or a tunnel.
"""

import json
import os
import sys

import pytest

from tpu_cooccurrence.bench import grant_watch


def _read_log(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def test_probe_cpu_backend_is_not_a_grant(monkeypatch):
    monkeypatch.setattr(grant_watch, "PROBE_CODE", "print('GRANT-cpu')")
    assert grant_watch.probe_once(timeout_s=60) is False


def test_probe_accelerator_backend_is_a_grant(monkeypatch):
    monkeypatch.setattr(grant_watch, "PROBE_CODE", "print('GRANT-tpu')")
    assert grant_watch.probe_once(timeout_s=60) is True


def test_probe_hang_times_out_false(monkeypatch):
    monkeypatch.setattr(grant_watch, "PROBE_CODE",
                        "import time; time.sleep(600)")
    assert grant_watch.probe_once(timeout_s=2) is False


def test_watch_no_grant_keeps_watching(monkeypatch, tmp_path):
    monkeypatch.setattr(grant_watch, "PROBE_CODE", "print('GRANT-cpu')")
    log = str(tmp_path / "watch.jsonl")
    captures = grant_watch.watch(interval_s=0, probe_timeout_s=60,
                                 max_cycles=3, log_path=log,
                                 stages=[], heartbeat_every=2)
    assert captures == 0
    events = [e["event"] for e in _read_log(log)]
    # Heartbeat throttle: cycles 1 and 3 log, cycle 2 is silent.
    assert events.count("no-grant") == 2
    assert "grant" not in events


def test_watch_captures_on_grant(monkeypatch, tmp_path):
    monkeypatch.setattr(grant_watch, "PROBE_CODE", "print('GRANT-tpu')")
    log = str(tmp_path / "watch.jsonl")
    marker = tmp_path / "stage-ran"
    stage_cmd = [sys.executable, "-c",
                 f"open({str(marker)!r}, 'w').write('ok'); print('done')"]
    captures = grant_watch.watch(
        interval_s=0, probe_timeout_s=60, max_captures=1, log_path=log,
        stages=[("stub", stage_cmd, 60.0)])
    assert captures == 1
    assert marker.read_text() == "ok"
    log_events = _read_log(log)
    by_event = {e["event"]: e for e in log_events}
    assert by_event["stage-end"]["ok"] is True
    assert "done" in by_event["stage-end"]["stdout_tail"]
    assert by_event["capture-done"]["complete"] is True


def test_watch_stage_timeout_then_grant_lost(monkeypatch, tmp_path):
    """A stage that outlives its deadline is killed; the re-probe sees
    the grant gone and the remaining stages are skipped, not hung."""
    flag = tmp_path / "grant-up"
    flag.write_text("1")
    # Probe keyed on the flag file; the hanging stage removes it first,
    # simulating a grant that dies mid-capture.
    monkeypatch.setattr(
        grant_watch, "PROBE_CODE",
        f"import os; print('GRANT-tpu' if os.path.exists({str(flag)!r}) "
        f"else 'GRANT-cpu')")
    hang_cmd = [sys.executable, "-c",
                f"import os, time; os.remove({str(flag)!r}); "
                f"time.sleep(600)"]
    never = tmp_path / "never"
    after_cmd = [sys.executable, "-c",
                 f"open({str(never)!r}, 'w').close()"]
    log = str(tmp_path / "watch.jsonl")
    # The hang stage's deadline must comfortably exceed interpreter
    # startup (measured >2.5 s under load) so os.remove runs before the
    # SIGKILL — the hang comes from the sleep, not slow startup.
    captures = grant_watch.watch(
        interval_s=0, probe_timeout_s=60, max_cycles=1, log_path=log,
        stages=[("hang", hang_cmd, 8.0), ("after", after_cmd, 60.0)])
    assert captures == 0  # incomplete sessions don't count as captures
    assert not never.exists(), "stages after grant-loss must be skipped"
    events = [e["event"] for e in _read_log(log)]
    assert "stage-timeout" in events
    assert "grant-lost" in events
    done = [e for e in _read_log(log) if e["event"] == "capture-done"]
    assert done and done[0]["complete"] is False
    assert done[0]["sessions"] == 1


def test_failed_measurement_with_live_grant_still_completes(
        monkeypatch, tmp_path):
    """A stage that exits nonzero while the grant survives (tpu_round2
    recording a failed measurement) is logged but does not void the
    session — one deterministically-failing measurement must not make a
    --max-captures watcher re-burn every future grant re-running the
    whole list. Timeouts/grant loss still do (previous tests)."""
    monkeypatch.setattr(grant_watch, "PROBE_CODE", "print('GRANT-tpu')")
    fail_cmd = [sys.executable, "-c", "import sys; sys.exit(1)"]
    after = tmp_path / "after-ran"
    after_cmd = [sys.executable, "-c",
                 f"open({str(after)!r}, 'w').close()"]
    log = str(tmp_path / "watch.jsonl")
    captures = grant_watch.watch(
        interval_s=0, probe_timeout_s=60, max_captures=1, log_path=log,
        stages=[("tpu_round2:bad-measurement", fail_cmd, 60.0),
                ("next", after_cmd, 60.0)])
    assert captures == 1
    assert after.exists(), "later stages must still run (grant is up)"
    done = [e for e in _read_log(log) if e["event"] == "capture-done"]
    assert done[0]["complete"] is True
    assert done[0]["failed_stages"] == ["tpu_round2:bad-measurement"]
    assert "grant-lost" not in [e["event"] for e in _read_log(log)]


def test_transient_failure_retried_once_then_captured(
        monkeypatch, tmp_path):
    """VERDICT r4 Next #2: a stage failing with a transient error
    signature (the 2026-07-31 `UNAVAILABLE` class) while the liveness
    probe stays green is retried with backoff; fail-once-then-succeed
    means one retry and a completed capture."""
    monkeypatch.setattr(grant_watch, "PROBE_CODE", "print('GRANT-tpu')")
    attempts = tmp_path / "attempts"
    flaky_cmd = [sys.executable, "-c", (
        f"import os, sys\n"
        f"p = {str(attempts)!r}\n"
        f"n = len(open(p).read()) if os.path.exists(p) else 0\n"
        f"open(p, 'a').write('x')\n"
        f"if n == 0:\n"
        f"    sys.stderr.write('UNAVAILABLE: TPU backend setup/compile "
        f"error\\n'); sys.exit(1)\n"
        f"print('captured')")]
    log = str(tmp_path / "watch.jsonl")
    captures = grant_watch.watch(
        interval_s=0, probe_timeout_s=60, max_captures=1, log_path=log,
        stages=[("tpu_round2:flaky", flaky_cmd, 60.0)],
        stage_retries=2, retry_backoff_s=0.0)
    assert captures == 1
    assert attempts.read_text() == "xx", "exactly one retry"
    events = [e["event"] for e in _read_log(log)]
    assert events.count("stage-retry") == 1
    retry = [e for e in _read_log(log) if e["event"] == "stage-retry"][0]
    assert retry["stage"] == "tpu_round2:flaky"
    assert retry["attempt"] == 1
    done = [e for e in _read_log(log) if e["event"] == "capture-done"][0]
    assert done["complete"] is True
    assert "failed_stages" not in done, "retried-to-success is a success"


def test_transient_failure_always_failing_moves_on(monkeypatch, tmp_path):
    """Fail-always exhausts the bounded retries and moves on — the
    retry loop must not wedge a session on one broken measurement."""
    monkeypatch.setattr(grant_watch, "PROBE_CODE", "print('GRANT-tpu')")
    attempts = tmp_path / "attempts"
    fail_cmd = [sys.executable, "-c", (
        f"import sys; open({str(attempts)!r}, 'a').write('x'); "
        f"sys.stderr.write('UNAVAILABLE: transient\\n'); sys.exit(1)")]
    after = tmp_path / "after-ran"
    after_cmd = [sys.executable, "-c",
                 f"open({str(after)!r}, 'w').close()"]
    log = str(tmp_path / "watch.jsonl")
    captures = grant_watch.watch(
        interval_s=0, probe_timeout_s=60, max_cycles=1, log_path=log,
        stages=[("tpu_round2:always-bad", fail_cmd, 60.0),
                ("next", after_cmd, 60.0)],
        stage_retries=2, retry_backoff_s=0.0)
    assert attempts.read_text() == "xxx", "initial run + 2 retries"
    assert after.exists(), "later stages still run after giving up"
    assert captures == 1, ("exhausted-retry measurement failure is a "
                           "recorded result, not a voided session")
    done = [e for e in _read_log(log) if e["event"] == "capture-done"][0]
    assert done["failed_stages"] == ["tpu_round2:always-bad"]


def test_deterministic_failure_not_retried(monkeypatch, tmp_path):
    """A nonzero exit WITHOUT a transient marker (assertion, shape bug)
    must not burn grant time on retries that cannot succeed."""
    monkeypatch.setattr(grant_watch, "PROBE_CODE", "print('GRANT-tpu')")
    attempts = tmp_path / "attempts"
    fail_cmd = [sys.executable, "-c", (
        f"import sys; open({str(attempts)!r}, 'a').write('x'); "
        f"sys.stderr.write('AssertionError: rows diverged\\n'); "
        f"sys.exit(1)")]
    log = str(tmp_path / "watch.jsonl")
    grant_watch.watch(
        interval_s=0, probe_timeout_s=60, max_cycles=1, log_path=log,
        stages=[("tpu_round2:det-bad", fail_cmd, 60.0)],
        stage_retries=2, retry_backoff_s=0.0)
    assert attempts.read_text() == "x", "no retries"
    assert "stage-retry" not in [e["event"] for e in _read_log(log)]


def test_transient_failure_with_dead_tunnel_not_retried(
        monkeypatch, tmp_path):
    """Retry is gated on a green liveness probe: a transient failure
    whose re-probe shows the grant gone skips the retry (and the
    session records grant-lost as before)."""
    flag = tmp_path / "grant-up"
    flag.write_text("1")
    monkeypatch.setattr(
        grant_watch, "PROBE_CODE",
        f"import os; print('GRANT-tpu' if os.path.exists({str(flag)!r}) "
        f"else 'GRANT-cpu')")
    attempts = tmp_path / "attempts"
    die_cmd = [sys.executable, "-c", (
        f"import os, sys; open({str(attempts)!r}, 'a').write('x'); "
        f"os.remove({str(flag)!r}); "
        f"sys.stderr.write('UNAVAILABLE: tunnel died\\n'); sys.exit(1)")]
    log = str(tmp_path / "watch.jsonl")
    grant_watch.watch(
        interval_s=0, probe_timeout_s=60, max_cycles=1, log_path=log,
        stages=[("tpu_round2:died", die_cmd, 60.0)],
        stage_retries=2, retry_backoff_s=0.0)
    assert attempts.read_text() == "x", "no retry on a dead tunnel"
    events = [e["event"] for e in _read_log(log)]
    assert "stage-retry" not in events
    assert "grant-lost" in events


def test_is_transient_failure_markers():
    assert grant_watch.is_transient_failure(
        "jaxlib...: UNAVAILABLE: TPU backend setup/compile error")
    assert grant_watch.is_transient_failure("DEADLINE_EXCEEDED: rpc")
    assert grant_watch.is_transient_failure("Socket closed")
    assert not grant_watch.is_transient_failure("AssertionError: boom")
    assert not grant_watch.is_transient_failure("")
    assert not grant_watch.is_transient_failure(None)


def test_capture_env_scrubs_measurement_knobs(monkeypatch, tmp_path):
    """ADVICE r4: stale operator exports of the upload-chunk and
    score-mode knobs must not reach capture stages — they would
    silently re-pin what the unpinned passes measure."""
    for k in ("TPU_COOC_SMOKE_EVENTS", "TPU_ROUND2_OUT",
              "TPU_COOC_UPLOAD_CHUNKS", "TPU_COOC_UPLOAD_CHUNK_KB",
              "TPU_COOC_SCORE_LADDER", "TPU_COOC_FIXED_SCORE"):
        monkeypatch.setenv(k, "stale")
    monkeypatch.setenv("TPU_COOC_HARMLESS", "kept")
    seen = tmp_path / "env.json"
    dump_cmd = [sys.executable, "-c", (
        "import json, os; "
        f"json.dump({{k: v for k, v in os.environ.items() "
        f"if k.startswith('TPU_')}}, open({str(seen)!r}, 'w'))")]
    status, _err = grant_watch.run_stage(
        "dump-env", dump_cmd, 60.0, str(tmp_path / "w.jsonl"))
    assert status == "ok"
    env = json.loads(seen.read_text())
    assert "TPU_COOC_SMOKE_EVENTS" not in env
    assert "TPU_ROUND2_OUT" not in env
    assert "TPU_COOC_UPLOAD_CHUNKS" not in env
    assert "TPU_COOC_UPLOAD_CHUNK_KB" not in env
    assert "TPU_COOC_SCORE_LADDER" not in env
    assert "TPU_COOC_FIXED_SCORE" not in env
    assert env.get("TPU_COOC_HARMLESS") == "kept"


def test_second_watcher_refuses_to_start(monkeypatch, tmp_path):
    """Two watchers would race duplicate captures on the scarce chip;
    the second instance must fail fast while the lock is held."""
    import fcntl

    monkeypatch.setattr(grant_watch, "PROBE_CODE", "print('GRANT-cpu')")
    log = str(tmp_path / "w.jsonl")
    holder = open(log + ".lock", "w")
    fcntl.flock(holder, fcntl.LOCK_EX | fcntl.LOCK_NB)
    with pytest.raises(SystemExit, match="another grant_watch"):
        grant_watch.watch(interval_s=0, probe_timeout_s=60,
                          max_cycles=1, log_path=log, stages=[])
    holder.close()   # released: now it can start
    assert grant_watch.watch(interval_s=0, probe_timeout_s=60,
                             max_cycles=1, log_path=log, stages=[]) == 0


def test_recapture_cooldown_pauses_chip_stages(monkeypatch, tmp_path):
    """After a COMPLETE capture the watcher must not hammer a
    still-live grant with back-to-back duplicate passes: chip stages
    pause for the cooldown (cycles tick, no probe/capture), while
    cooldown=0 recaptures immediately."""
    monkeypatch.setattr(grant_watch, "PROBE_CODE", "print('GRANT-tpu')")
    ok_cmd = [sys.executable, "-c", "print('ok')"]
    log = str(tmp_path / "watch.jsonl")
    grant_watch.watch(
        interval_s=0, probe_timeout_s=60, max_cycles=3, log_path=log,
        stages=[("stub", ok_cmd, 60.0)], recapture_cooldown_s=3600.0)
    events = [e["event"] for e in _read_log(log)]
    assert events.count("grant") == 1, "cooldown must suppress recapture"
    assert events.count("capture-done") == 1
    log2 = str(tmp_path / "watch2.jsonl")
    grant_watch.watch(
        interval_s=0, probe_timeout_s=60, max_cycles=2, log_path=log2,
        stages=[("stub", ok_cmd, 60.0)], recapture_cooldown_s=0.0)
    events = [e["event"] for e in _read_log(log2)]
    assert events.count("capture-done") == 2, "cooldown=0 recaptures"


def test_headline_group_failure_voids_completeness(monkeypatch, tmp_path):
    """If every ran member of a REQUIRED_STAGE_GROUPS headline group
    fails (the 2026-07-31 transient-UNAVAILABLE class hitting all
    config-4 forms), the session is not a usable capture — a
    --max-captures watcher must keep watching. A succeeding ALTERNATIVE
    member of the group keeps the session complete."""
    monkeypatch.setattr(grant_watch, "PROBE_CODE", "print('GRANT-tpu')")
    fail_cmd = [sys.executable, "-c", "import sys; sys.exit(1)"]
    ok_cmd = [sys.executable, "-c", "print('ok')"]
    log = str(tmp_path / "watch.jsonl")
    captures = grant_watch.watch(
        interval_s=0, probe_timeout_s=60, max_cycles=1, log_path=log,
        stages=[("tpu_round2:config4-headline", fail_cmd, 60.0),
                ("tpu_round2:config4-chunked", fail_cmd, 60.0)])
    assert captures == 0
    done = [e for e in _read_log(log) if e["event"] == "capture-done"]
    assert done[0]["complete"] is False
    assert done[0]["missing_headline_groups"] == [[
        "tpu_round2:config4-headline", "tpu_round2:config4-chunked",
        "tpu_round2:config4-sparse"]]
    # The sweep form succeeding satisfies the group (OR semantics: a
    # deterministically-failing variant can't wedge the watcher).
    log2 = str(tmp_path / "watch2.jsonl")
    captures = grant_watch.watch(
        interval_s=0, probe_timeout_s=60, max_cycles=1, log_path=log2,
        stages=[("tpu_round2:config4-headline", fail_cmd, 60.0),
                ("tpu_round2:config4-sparse", ok_cmd, 60.0)])
    assert captures == 1
    done = [e for e in _read_log(log2) if e["event"] == "capture-done"]
    assert done[0]["complete"] is True
    assert "missing_headline_groups" not in done[0]


def test_failed_artifact_stage_voids_completeness(monkeypatch, tmp_path):
    """A failed NON-measurement stage (bench.py, summarize) means the
    session's deliverable is missing: complete must be False even with
    the grant up, so --max-captures keeps watching for a usable one."""
    monkeypatch.setattr(grant_watch, "PROBE_CODE", "print('GRANT-tpu')")
    fail_cmd = [sys.executable, "-c", "import sys; sys.exit(1)"]
    log = str(tmp_path / "watch.jsonl")
    captures = grant_watch.watch(
        interval_s=0, probe_timeout_s=60, max_cycles=1, log_path=log,
        stages=[("bench.py", fail_cmd, 60.0)])
    assert captures == 0
    done = [e for e in _read_log(log) if e["event"] == "capture-done"]
    assert done[0]["complete"] is False
    assert done[0]["failed_stages"] == ["bench.py"]


def test_offline_stage_runs_after_grant_loss(monkeypatch, tmp_path):
    """Stages marked needs_grant=False (the summary rewrite) still run
    after a mid-capture grant death — the partial capture's fresh JSONL
    rows must reach the summary artifact."""
    flag = tmp_path / "grant-up"
    flag.write_text("1")
    monkeypatch.setattr(
        grant_watch, "PROBE_CODE",
        f"import os; print('GRANT-tpu' if os.path.exists({str(flag)!r}) "
        f"else 'GRANT-cpu')")
    die_cmd = [sys.executable, "-c",
               f"import os, sys; os.remove({str(flag)!r}); sys.exit(1)"]
    skipped = tmp_path / "skipped-chip-stage"
    chip_cmd = [sys.executable, "-c",
                f"open({str(skipped)!r}, 'w').close()"]
    offline = tmp_path / "offline-ran"
    offline_cmd = [sys.executable, "-c",
                   f"open({str(offline)!r}, 'w').close()"]
    log = str(tmp_path / "watch.jsonl")
    grant_watch.watch(
        interval_s=0, probe_timeout_s=60, max_cycles=1, log_path=log,
        stages=[("die", die_cmd, 60.0),
                ("chip", chip_cmd, 60.0),          # needs grant: skipped
                ("offline", offline_cmd, 60.0, False)])
    assert not skipped.exists(), "chip stage must be skipped after loss"
    assert offline.exists(), "offline stage must run after grant loss"
    events = [e["event"] for e in _read_log(log)]
    assert "grant-lost" in events


def test_default_stages_shape():
    stages = grant_watch.default_stages()
    names = [s[0] for s in stages]
    # Per-measurement stages (own deadline each: a hanging measurement
    # costs one deadline, not the rest of a monolithic pass), headline
    # numbers first, then the official bench artifact and the offline
    # summary rewrite.
    assert names[0] == "tpu_round2:tunnel-probe"
    assert names[1] == "tpu_round2:config4-headline"
    assert "tpu_round2:ml25m-sparse" in names
    assert "tpu_round2:ml25m-full" in names
    assert "tpu_round2:sparse-pallas" in names
    assert names[-2:] == ["bench.py", "summarize"]
    for s in stages:
        assert s[1][0] == sys.executable
        assert s[2] > 0
    for s in stages:
        if s[0].startswith("tpu_round2:"):
            only = s[1][s[1].index("--only") + 1]
            assert s[0] == f"tpu_round2:{only}"
    # Only the offline summary rewrite survives a grant loss.
    assert [s[3] if len(s) > 3 else True for s in stages] == (
        [True] * (len(stages) - 1) + [False])
    quick = grant_watch.default_stages(quick=True)
    assert all("--quick" in s[1] for s in quick
               if s[0].startswith("tpu_round2:"))
    # Quick deadlines are tighter than full ones, stage by stage.
    for full_s, quick_s in zip(stages, quick):
        assert quick_s[2] <= full_s[2]


def test_status_summarizes_log(tmp_path):
    log = tmp_path / "w.jsonl"
    rows = [
        {"ts": "t0", "event": "watch-start"},
        {"ts": "t1", "event": "no-grant", "cycle": 1},
        {"ts": "t2", "event": "grant", "cycle": 5},
        {"ts": "t2b", "event": "stage-retry", "cycle": 5,
         "stage": "tpu_round2:x", "attempt": 1},
        {"ts": "t3", "event": "capture-done", "complete": False,
         "cycle": 5},
        {"ts": "t4", "event": "grant", "cycle": 9},
        {"ts": "t5", "event": "capture-done", "complete": True,
         "cycle": 9},
        {"ts": "t6", "event": "no-grant", "cycle": 13},
    ]
    with open(log, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    s = grant_watch.status(str(log))
    assert s["first_ts"] == "t0" and s["last_ts"] == "t6"
    assert s["cycles"] == 13
    assert s["grants"] == 2
    assert s["stage_retries"] == 1
    assert s["captures_complete"] == 1
    assert s["last_capture_ts"] == "t5"
    missing = grant_watch.status(str(tmp_path / "none.jsonl"))
    assert missing["exists"] is False
    # Cycles accumulate across restarted watch runs: a clean first run
    # of 12 (from its watch-end total — heartbeats undercount) plus an
    # in-progress second run at cycle 3.
    with open(log, "w") as f:
        for r in ({"ts": "a", "event": "watch-start"},
                  {"ts": "b", "event": "no-grant", "cycle": 1},
                  {"ts": "c", "event": "watch-end", "cycles": 12},
                  {"ts": "d", "event": "watch-start"},
                  {"ts": "e", "event": "no-grant", "cycle": 3}):
            f.write(json.dumps(r) + "\n")
    s2 = grant_watch.status(str(log))
    assert s2["cycles"] == 15
    # probes_run sums watch-end probes (falling back to cycles for
    # pre-cooldown rows without the field); in-flight runs trail.
    assert s2["probes_run"] == 12
