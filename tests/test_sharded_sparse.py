"""Sharded-sparse backend tests (virtual 8-device CPU mesh)."""

import numpy as np
import pytest

from tpu_cooccurrence.config import Backend, Config
from tpu_cooccurrence.job import CooccurrenceJob
from tpu_cooccurrence.metrics import (
    OBSERVED_COOCCURRENCES,
    RESCORED_ITEMS,
    ROW_SUM_PROCESS_WINDOW,
)

from test_pipeline import (
    assert_latest_close,
    random_stream,
    relabel_first_appearance,
    run_production,
)


@pytest.mark.parametrize("overrides", [
    dict(skip_cuts=True),
    dict(item_cut=5, user_cut=4),
])
@pytest.mark.parametrize("shards", [2, 8])
def test_sharded_sparse_matches_oracle(shards, overrides):
    kw = dict(window_size=10, seed=0xBEEF, development_mode=True)
    kw.update(overrides)
    users, items, ts = random_stream(31)
    a = run_production(Config(**kw, backend=Backend.ORACLE), users, items, ts)
    b = run_production(Config(**kw, backend=Backend.SPARSE,
                              num_shards=shards), users, items, ts)
    assert_latest_close(a.latest, b.latest)
    for name in (OBSERVED_COOCCURRENCES, ROW_SUM_PROCESS_WINDOW,
                 RESCORED_ITEMS):
        assert a.counters.get(name) == b.counters.get(name), name


def test_sharded_sparse_matches_single_device_sparse():
    """Shard count must not change results at all (same f32 math, same
    insertion-order tie-breaking within each row)."""
    kw = dict(window_size=20, seed=0xD2, item_cut=6, user_cut=4)
    rng = np.random.default_rng(13)
    n = 2000
    users = relabel_first_appearance(rng.integers(0, 12, n))
    items = relabel_first_appearance(rng.integers(0, 120, n))
    ts = np.cumsum(rng.integers(0, 2, n)).astype(np.int64)
    a = run_production(Config(**kw, backend=Backend.SPARSE),
                       users, items, ts)
    b = run_production(Config(**kw, backend=Backend.SPARSE, num_shards=8),
                       users, items, ts)
    assert set(a.latest) == set(b.latest)
    for item in a.latest:
        assert a.latest[item] == b.latest[item], f"row {item}"
    assert a.counters.as_dict() == b.counters.as_dict()


def test_sharded_sparse_growth_and_compaction():
    from tpu_cooccurrence.parallel.sharded_sparse import ShardedSparseScorer

    kw = dict(window_size=20, seed=0xD3, skip_cuts=True,
              development_mode=True)
    rng = np.random.default_rng(17)
    n = 2500
    users = relabel_first_appearance(rng.integers(0, 8, n))
    items = relabel_first_appearance(rng.integers(0, 150, n))
    ts = np.cumsum(rng.integers(0, 2, n)).astype(np.int64)
    a = run_production(Config(**kw, backend=Backend.ORACLE), users, items, ts)
    cfg = Config(**kw, backend=Backend.SPARSE, num_shards=4)
    scorer = ShardedSparseScorer(cfg.top_k, num_shards=4,
                                 development_mode=True, capacity=64,
                                 items_capacity=32, compact_min_heap=128)
    job = CooccurrenceJob(cfg, scorer=scorer)
    scorer.counters = job.counters
    for lo in range(0, n, 97):
        job.add_batch(users[lo:lo + 97], items[lo:lo + 97], ts[lo:lo + 97])
    job.finish()
    assert scorer.capacity > 64
    assert scorer.items_cap > 32
    assert sum(ix.compactions for ix in scorer.indexes) > 0
    assert_latest_close(a.latest, job.latest)


def test_sharded_sparse_checkpoint_interchange(tmp_path):
    """Canonical format: 1-shard checkpoint restores onto 8 shards and an
    8-shard checkpoint restores onto the single-device sparse backend."""
    users, items, ts = random_stream(35, n=400)
    half = 200
    for first_shards, second_shards in [(1, 8), (8, 1)]:
        kw = dict(window_size=10, seed=9, item_cut=5, user_cut=3,
                  development_mode=True,
                  checkpoint_dir=str(tmp_path / f"ck-{first_shards}"))
        ref = CooccurrenceJob(Config(**kw, backend=Backend.SPARSE,
                                     num_shards=second_shards))
        ref.add_batch(users, items, ts)
        ref.finish()

        a = CooccurrenceJob(Config(**kw, backend=Backend.SPARSE,
                                   num_shards=first_shards))
        a.add_batch(users[:half], items[:half], ts[:half])
        a.checkpoint()
        b = CooccurrenceJob(Config(**kw, backend=Backend.SPARSE,
                                   num_shards=second_shards))
        b.restore()
        b.add_batch(users[half:], items[half:], ts[half:])
        b.finish()
        assert_latest_close(ref.latest, b.latest, rtol=1e-5, atol=1e-5)


def test_sharded_sparse_deferred_matches_pipelined():
    """Deferred results (job default) == per-window pipeline (the
    --emit-updates path) on the virtual mesh, and no mid-stream
    emissions under deferral."""
    kw = dict(window_size=10, seed=0xA7, item_cut=5, user_cut=4,
              development_mode=True)
    users, items, ts = random_stream(67, n=1500)

    def run(emit):
        cfg = Config(**kw, backend=Backend.SPARSE, num_shards=8,
                     emit_updates=emit)
        job = CooccurrenceJob(cfg)
        mid = []
        job.on_update = lambda batch: mid.append(len(batch))
        job.add_batch(users, items, ts)
        n_mid = sum(mid)
        job.finish()
        return job, n_mid

    piped, mid_p = run(True)
    assert not piped.scorer.defer_results
    deferred, mid_d = run(False)
    assert deferred.scorer.defer_results
    assert mid_p > 0
    assert mid_d == 0
    assert_latest_close(piped.latest, deferred.latest,
                        rtol=1e-6, atol=1e-6)


def test_sharded_sparse_deferred_growth_and_checkpoint(tmp_path):
    """Deferred table survives items-capacity growth; periodic checkpoint
    + restore matches an uninterrupted run."""
    kw = dict(window_size=10, seed=0xA8, item_cut=5, user_cut=3,
              backend=Backend.SPARSE, num_shards=4,
              checkpoint_dir=str(tmp_path / "ck"), development_mode=True)
    rng = np.random.default_rng(17)
    n = 2600
    users = relabel_first_appearance(rng.integers(0, 15, n))
    items = relabel_first_appearance(rng.integers(0, 6000, n))
    ts = np.cumsum(rng.integers(0, 2, n)).astype(np.int64)
    half = 1300

    ref = CooccurrenceJob(Config(**kw))
    # Tiny capacity so the stream forces table growth mid-run.
    from tpu_cooccurrence.parallel.sharded_sparse import ShardedSparseScorer

    def tiny(cfg):
        sc = ShardedSparseScorer(cfg.top_k, num_shards=4,
                                 development_mode=True,
                                 items_capacity=1024,
                                 defer_results=True)
        job = CooccurrenceJob(cfg, scorer=sc)
        sc.counters = job.counters
        return job

    ref2 = tiny(Config(**kw))
    ref2.add_batch(users, items, ts)
    ref2.finish()
    assert ref2.scorer.items_cap > 1024  # growth actually happened
    ref.add_batch(users, items, ts)
    ref.finish()
    assert_latest_close(ref.latest, ref2.latest, rtol=1e-6, atol=1e-6)

    a = CooccurrenceJob(Config(**kw))
    a.add_batch(users[:half], items[:half], ts[:half])
    a.checkpoint()
    b = CooccurrenceJob(Config(**kw))
    b.restore()
    b.add_batch(users[half:], items[half:], ts[half:])
    b.finish()
    assert_latest_close(ref.latest, b.latest, rtol=1e-6, atol=1e-6)


def test_sharded_sparse_fixed_shapes_matches_variable():
    """Sharded fixed-shape scoring (one fused shard_map dispatch per
    window over a shard-uniform monotone plan) == the variable ladder."""
    from tpu_cooccurrence.parallel.sharded_sparse import ShardedSparseScorer

    kw = dict(window_size=10, seed=0xF7, item_cut=5, user_cut=4,
              development_mode=True)
    users, items, ts = random_stream(73, n=1500)

    def run(fixed):
        cfg = Config(**kw, backend=Backend.SPARSE, num_shards=8)
        scorer = ShardedSparseScorer(cfg.top_k, num_shards=8,
                                     development_mode=True,
                                     defer_results=True,
                                     fixed_shapes=fixed)
        if fixed:
            scorer.FIXED_BUDGET = 1 << 12
            scorer.FIXED_ROW_CAP = 64
        job = CooccurrenceJob(cfg, scorer=scorer)
        scorer.counters = job.counters
        job.add_batch(users, items, ts)
        job.finish()
        return job

    var = run(False)
    fix = run(True)
    assert_latest_close(var.latest, fix.latest, rtol=1e-6, atol=1e-6)
    for name in (OBSERVED_COOCCURRENCES, ROW_SUM_PROCESS_WINDOW,
                 RESCORED_ITEMS):
        assert var.counters.get(name) == fix.counters.get(name), name
