"""Native C++ expansion kernels vs the NumPy fallback path."""

import numpy as np
import pytest

from tpu_cooccurrence import native
from tpu_cooccurrence.sampling.reservoir import UserReservoirSampler


def _run_fire(monkeypatch, force_fallback: bool):
    if force_fallback:
        monkeypatch.setattr(native, "expand_replacements",
                            lambda *a, **k: None)
        monkeypatch.setattr(native, "expand_appends",
                            lambda *a, **k: None)
    rng = np.random.default_rng(7)
    s = UserReservoirSampler(user_cut=4, seed=11, skip_cuts=False)
    outs = []
    for _ in range(10):
        n = 60
        users = rng.integers(0, 5, n).astype(np.int64)
        items = rng.integers(0, 30, n).astype(np.int64)
        pairs, fb = s.fire(users, items, np.ones(n, dtype=bool))
        outs.append((pairs.src.copy(), pairs.dst.copy(), pairs.delta.copy(),
                     fb.copy()))
    return outs, s.hist.copy(), s.hist_len.copy()


@pytest.mark.skipif(native.get_lib() is None, reason="no native lib (g++)")
def test_native_matches_numpy_fallback(monkeypatch):
    nat, nat_hist, nat_len = _run_fire(monkeypatch, force_fallback=False)
    fall, fall_hist, fall_len = _run_fire(monkeypatch, force_fallback=True)
    assert len(nat) == len(fall)
    for (ns, nd, nv, nf), (fs, fd, fv, ff) in zip(nat, fall):
        # Aggregated deltas must be identical (emission order may differ
        # between the native block layout and the numpy per-event blocks).
        def agg(s, d, v):
            out = {}
            for a, b, c in zip(s.tolist(), d.tolist(), v.tolist()):
                out[(a, b)] = out.get((a, b), 0) + c
            return {k: v for k, v in out.items() if v != 0}
        assert agg(ns, nd, nv) == agg(fs, fd, fv)
        np.testing.assert_array_equal(nf, ff)
    np.testing.assert_array_equal(nat_hist, fall_hist)
    np.testing.assert_array_equal(nat_len, fall_len)


def test_grouped_rank_native_matches_numpy():
    import pytest

    import tpu_cooccurrence.native as native
    from tpu_cooccurrence.sampling.item_cut import grouped_rank

    if native.get_lib() is None:
        pytest.skip("native library unavailable")
    rng = np.random.default_rng(0x6E0)
    for n, hi in ((513, 3), (2000, 50), (5000, 5000), (600, 1)):
        keys = rng.integers(0, hi, n).astype(np.int64)
        got = grouped_rank(keys)           # native path (n > 512)
        saved = native.grouped_rank_dense
        native.grouped_rank_dense = lambda *a: None
        try:
            want = grouped_rank(keys)      # argsort fallback
        finally:
            native.grouped_rank_dense = saved
        np.testing.assert_array_equal(got, want)


def test_grouped_rank_guards_sparse_and_negative_keys():
    """Negative or huge-sparse key spaces must take the argsort fallback
    (the native pass indexes a scratch array by key)."""
    from tpu_cooccurrence.sampling.item_cut import grouped_rank

    rng = np.random.default_rng(0x6E1)
    neg = rng.integers(-5, 5, 1000).astype(np.int64)
    got = grouped_rank(neg)
    # Oracle by dict counting.
    counts = {}
    want = np.empty(len(neg), dtype=np.int64)
    for i, k in enumerate(neg.tolist()):
        want[i] = counts.get(k, 0)
        counts[k] = want[i] + 1
    np.testing.assert_array_equal(got, want)

    sparse_keys = rng.integers(0, 2**40, 1000).astype(np.int64)
    got = grouped_rank(sparse_keys)  # must not allocate a 2^40 scratch
    counts = {}
    want = np.empty(len(sparse_keys), dtype=np.int64)
    for i, k in enumerate(sparse_keys.tolist()):
        want[i] = counts.get(k, 0)
        counts[k] = want[i] + 1
    np.testing.assert_array_equal(got, want)
