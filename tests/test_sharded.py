"""Multi-chip sharded backend tests on the 8-device virtual CPU mesh.

The sharded ``shard_map`` path must produce identical results to the
single-device backend (same f32 math, different partitioning) and match the
float64 oracle within tolerance. This is the SURVEY §4 strategy: validate
``psum``/sharding semantics without real TPUs."""

import numpy as np
import pytest

import jax

from tpu_cooccurrence.config import Backend, Config
from tpu_cooccurrence.job import CooccurrenceJob
from tpu_cooccurrence.metrics import (
    OBSERVED_COOCCURRENCES,
    RESCORED_ITEMS,
    ROW_SUM_PROCESS_WINDOW,
)

from test_pipeline import random_stream, run_production


requires_mesh = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices")


@requires_mesh
@pytest.mark.parametrize("overrides", [
    dict(skip_cuts=True),
    dict(item_cut=5, user_cut=4),
    dict(item_cut=3, user_cut=2, window_size=25),
])
def test_sharded_matches_single_device(overrides):
    kw = dict(window_size=10, seed=0xBEEF, num_items=30)
    kw.update(overrides)
    users, items, ts = random_stream(4)
    single = run_production(Config(**kw, backend=Backend.DEVICE), users, items, ts)
    sharded = run_production(
        Config(**kw, backend=Backend.SHARDED, num_shards=8), users, items, ts)
    assert set(single.latest) == set(sharded.latest)
    for item in single.latest:
        s = single.latest[item]
        m = sharded.latest[item]
        assert [j for j, _ in s] == [j for j, _ in m]
        np.testing.assert_allclose(
            np.array([v for _, v in m]), np.array([v for _, v in s]),
            rtol=1e-6, atol=1e-6)
    for name in (OBSERVED_COOCCURRENCES, ROW_SUM_PROCESS_WINDOW, RESCORED_ITEMS):
        assert single.counters.get(name) == sharded.counters.get(name), name


@requires_mesh
def test_sharded_matches_oracle():
    kw = dict(window_size=10, seed=7, item_cut=6, user_cut=4, num_items=30)
    users, items, ts = random_stream(12)
    oracle = run_production(Config(**kw, backend=Backend.ORACLE), users, items, ts)
    sharded = run_production(
        Config(**kw, backend=Backend.SHARDED, num_shards=8), users, items, ts)
    assert set(oracle.latest) == set(sharded.latest)
    for item in oracle.latest:
        o_scores = np.array([v for _, v in oracle.latest[item]])
        m_scores = np.array([v for _, v in sharded.latest[item]])
        assert len(o_scores) == len(m_scores)
        np.testing.assert_allclose(m_scores, o_scores, rtol=1e-4, atol=1e-3)


@requires_mesh
def test_sharded_vocab_padding():
    # num_items not divisible by shards: padded internally, results unchanged.
    kw = dict(window_size=10, seed=5, skip_cuts=True, num_items=27)
    users, items, ts = random_stream(6)
    single = run_production(Config(**kw, backend=Backend.DEVICE), users, items, ts)
    sharded = run_production(
        Config(**kw, backend=Backend.SHARDED, num_shards=8), users, items, ts)
    assert set(single.latest) == set(sharded.latest)
