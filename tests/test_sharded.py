"""Multi-chip sharded backend tests on the 8-device virtual CPU mesh.

The sharded ``shard_map`` path must produce identical results to the
single-device backend (same f32 math, different partitioning) and match the
float64 oracle within tolerance. This is the SURVEY §4 strategy: validate
``psum``/sharding semantics without real TPUs."""

import numpy as np
import pytest

import jax

from tpu_cooccurrence.config import Backend, Config
from tpu_cooccurrence.job import CooccurrenceJob
from tpu_cooccurrence.metrics import (
    OBSERVED_COOCCURRENCES,
    RESCORED_ITEMS,
    ROW_SUM_PROCESS_WINDOW,
)

from test_pipeline import random_stream, run_production


requires_mesh = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices")


@requires_mesh
@pytest.mark.parametrize("overrides", [
    dict(skip_cuts=True),
    dict(item_cut=5, user_cut=4),
    dict(item_cut=3, user_cut=2, window_size=25),
])
def test_sharded_matches_single_device(overrides):
    kw = dict(window_size=10, seed=0xBEEF, num_items=30)
    kw.update(overrides)
    users, items, ts = random_stream(4)
    single = run_production(Config(**kw, backend=Backend.DEVICE), users, items, ts)
    sharded = run_production(
        Config(**kw, backend=Backend.SHARDED, num_shards=8), users, items, ts)
    assert set(single.latest) == set(sharded.latest)
    for item in single.latest:
        s = single.latest[item]
        m = sharded.latest[item]
        assert [j for j, _ in s] == [j for j, _ in m]
        np.testing.assert_allclose(
            np.array([v for _, v in m]), np.array([v for _, v in s]),
            rtol=1e-6, atol=1e-6)
    for name in (OBSERVED_COOCCURRENCES, ROW_SUM_PROCESS_WINDOW, RESCORED_ITEMS):
        assert single.counters.get(name) == sharded.counters.get(name), name


@requires_mesh
def test_sharded_matches_oracle():
    kw = dict(window_size=10, seed=7, item_cut=6, user_cut=4, num_items=30)
    users, items, ts = random_stream(12)
    oracle = run_production(Config(**kw, backend=Backend.ORACLE), users, items, ts)
    sharded = run_production(
        Config(**kw, backend=Backend.SHARDED, num_shards=8), users, items, ts)
    assert set(oracle.latest) == set(sharded.latest)
    for item in oracle.latest:
        o_scores = np.array([v for _, v in oracle.latest[item]])
        m_scores = np.array([v for _, v in sharded.latest[item]])
        assert len(o_scores) == len(m_scores)
        np.testing.assert_allclose(m_scores, o_scores, rtol=1e-4, atol=1e-3)


@requires_mesh
def test_sharded_derives_vocab_from_data():
    """No --num-items: the sharded backend starts at its auto capacity
    (64 rows/shard) and doubles-with-reshard on growth; a 700-item stream
    forces at least one growth past the 512-row initial mesh capacity and
    the results still match the (also derive-from-data) dense backend."""
    kw = dict(window_size=10, seed=0x5EED, item_cut=6, user_cut=4)
    users, items, ts = random_stream(9, n=1500, n_users=20, n_items=700)
    single = run_production(Config(**kw, backend=Backend.DEVICE), users, items, ts)
    sharded = run_production(
        Config(**kw, backend=Backend.SHARDED, num_shards=8), users, items, ts)
    assert sharded.scorer.auto_grow
    assert sharded.scorer.num_items > sharded.scorer.AUTO_INITIAL_ROWS * 8
    assert set(single.latest) == set(sharded.latest)
    for item in single.latest:
        s, m = single.latest[item], sharded.latest[item]
        assert [j for j, _ in s] == [j for j, _ in m]
        np.testing.assert_allclose(
            np.array([v for _, v in m]), np.array([v for _, v in s]),
            rtol=1e-6, atol=1e-6)


@requires_mesh
def test_sharded_autogrow_checkpoint_roundtrip(tmp_path):
    """Checkpoint an auto-grown sharded run mid-stream; restore into a
    fresh derive-from-data job (which starts at the small initial
    capacity and must adopt the checkpoint's) and finish identically."""
    from tpu_cooccurrence.job import CooccurrenceJob

    kw = dict(window_size=10, seed=3, item_cut=6, user_cut=4,
              backend=Backend.SHARDED, num_shards=8,
              checkpoint_dir=str(tmp_path / "ck"))
    users, items, ts = random_stream(10, n=2000, n_users=20, n_items=700)
    half = 1500  # deep enough that growth fired before the checkpoint

    ref = CooccurrenceJob(Config(**kw))
    ref.add_batch(users, items, ts)
    ref.finish()

    a = CooccurrenceJob(Config(**kw))
    a.add_batch(users[:half], items[:half], ts[:half])
    a.checkpoint()
    assert a.scorer.num_items > a.scorer.AUTO_INITIAL_ROWS * 8

    b = CooccurrenceJob(Config(**kw))
    b.restore()
    b.add_batch(users[half:], items[half:], ts[half:])
    b.finish()
    assert set(ref.latest) == set(b.latest)
    for item in ref.latest:
        np.testing.assert_allclose(
            np.array([v for _, v in b.latest[item]]),
            np.array([v for _, v in ref.latest[item]]),
            rtol=1e-6, atol=1e-6)


@requires_mesh
def test_sharded_restore_never_shrinks_below_configured_capacity(tmp_path):
    """Restoring a small checkpoint into a job with a larger --num-items
    must keep the configured capacity (items past the checkpoint's vocab
    would otherwise map to out-of-range shard owners mid-stream)."""
    from tpu_cooccurrence.job import CooccurrenceJob

    users, items, ts = random_stream(14, n=300, n_items=20)
    small = CooccurrenceJob(Config(
        window_size=10, seed=5, skip_cuts=True, backend=Backend.SHARDED,
        num_shards=8, num_items=32, checkpoint_dir=str(tmp_path / "ck")))
    small.add_batch(users, items, ts)
    small.checkpoint()

    big = CooccurrenceJob(Config(
        window_size=10, seed=5, skip_cuts=True, backend=Backend.SHARDED,
        num_shards=8, num_items=1000, checkpoint_dir=str(tmp_path / "ck")))
    big.restore()
    assert big.scorer.num_items >= 1000
    # And the tail of the configured vocab is actually usable.
    users2, items2, ts2 = random_stream(15, n=300, n_items=900)
    big.add_batch(users2, items2, ts2 + int(ts[-1]) + 20)
    big.finish()
    assert big.latest


@requires_mesh
def test_sharded_vocab_padding():
    # num_items not divisible by shards: padded internally, results unchanged.
    kw = dict(window_size=10, seed=5, skip_cuts=True, num_items=27)
    users, items, ts = random_stream(6)
    single = run_production(Config(**kw, backend=Backend.DEVICE), users, items, ts)
    sharded = run_production(
        Config(**kw, backend=Backend.SHARDED, num_shards=8), users, items, ts)
    assert set(single.latest) == set(sharded.latest)
