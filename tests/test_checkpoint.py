"""Checkpoint/resume: a restored job must continue bit-identically.

Closes the reference's fault-tolerance gap (SURVEY §5): rescorer state,
reservoirs, window buffers, and the source offset all survive."""

import numpy as np
import pytest

from tpu_cooccurrence.config import Backend, Config
from tpu_cooccurrence.io.source import FileMonitorSource
from tpu_cooccurrence.job import CooccurrenceJob

from test_pipeline import assert_latest_equal, random_stream


def make_cfg(tmp_path, backend=Backend.ORACLE, **kw):
    kw.setdefault("window_size", 10)
    kw.setdefault("seed", 0xABCD)
    kw.setdefault("item_cut", 5)
    kw.setdefault("user_cut", 3)
    kw.setdefault("development_mode", True)
    if backend != Backend.ORACLE:
        kw.setdefault("num_items", 32)
    return Config(backend=backend, checkpoint_dir=str(tmp_path / "ckpt"), **kw)


def test_resume_equals_uninterrupted(tmp_path):
    users, items, ts = random_stream(21, n=500)
    half = 230  # mid-stream, mid-window

    # Uninterrupted run.
    ref = CooccurrenceJob(make_cfg(tmp_path))
    ref.add_batch(users, items, ts)
    ref.finish()

    # Run A: process half, checkpoint, abandon.
    a = CooccurrenceJob(make_cfg(tmp_path))
    a.add_batch(users[:half], items[:half], ts[:half])
    a.checkpoint()

    # Run B: fresh job, restore, continue.
    b = CooccurrenceJob(make_cfg(tmp_path))
    b.restore()
    b.add_batch(users[half:], items[half:], ts[half:])
    b.finish()

    assert_latest_equal(ref.latest, b.latest)
    assert ref.counters.as_dict() == b.counters.as_dict()
    assert ref.windows_fired == b.windows_fired


def test_resume_device_backend(tmp_path):
    users, items, ts = random_stream(22, n=400)
    half = 190

    ref = CooccurrenceJob(make_cfg(tmp_path, backend=Backend.DEVICE))
    ref.add_batch(users, items, ts)
    ref.finish()

    a = CooccurrenceJob(make_cfg(tmp_path, backend=Backend.DEVICE))
    a.add_batch(users[:half], items[:half], ts[:half])
    a.checkpoint()

    b = CooccurrenceJob(make_cfg(tmp_path, backend=Backend.DEVICE))
    b.restore()
    b.add_batch(users[half:], items[half:], ts[half:])
    b.finish()

    assert set(ref.latest) == set(b.latest)
    for item in ref.latest:
        np.testing.assert_allclose(
            np.array([v for _, v in b.latest[item]]),
            np.array([v for _, v in ref.latest[item]]), rtol=1e-6, atol=1e-6)


def test_config_mismatch_rejected(tmp_path):
    users, items, ts = random_stream(23, n=100)
    a = CooccurrenceJob(make_cfg(tmp_path))
    a.add_batch(users, items, ts)
    a.checkpoint()
    bad = CooccurrenceJob(make_cfg(tmp_path, user_cut=7))
    try:
        bad.restore()
    except ValueError as e:
        assert "user_cut" in str(e)
    else:
        raise AssertionError("expected config-mismatch ValueError")


def test_source_offset_survives(tmp_path):
    f = tmp_path / "in.csv"
    f.write_text("1,10,1\n1,11,2\n")
    cfg = make_cfg(tmp_path)
    job = CooccurrenceJob(cfg)
    src = FileMonitorSource(str(f), job.counters)
    lines = list(src.lines())
    assert len(lines) == 2
    job.checkpoint(source=src)

    job2 = CooccurrenceJob(make_cfg(tmp_path))
    src2 = FileMonitorSource(str(f), job2.counters)
    job2.restore(source=src2)
    # Same file, same mtime: already consumed -> no re-ingest.
    assert list(src2.lines()) == []


def test_periodic_checkpointing(tmp_path):
    cfg = make_cfg(tmp_path, checkpoint_every_windows=2)
    users, items, ts = random_stream(24, n=300)
    job = CooccurrenceJob(cfg)
    job.add_batch(users, items, ts)
    job.finish()
    gens = sorted((tmp_path / "ckpt").glob("state.*.npz"))
    assert gens, "no generation-numbered checkpoint landed"
    assert (tmp_path / "ckpt" / "LATEST").read_text().strip() == \
        max(gens, key=lambda p: int(p.name.split(".")[1])).name
    assert (tmp_path / "ckpt" / "meta.json").exists()


def test_restore_across_vocab_padding_change(tmp_path):
    """A checkpoint written with pallas vocab padding restores after the
    default flipped to the unpadded XLA path (and vice versa)."""
    from tpu_cooccurrence.ops.device_scorer import DeviceScorer

    rng = np.random.default_rng(5)
    padded = DeviceScorer(40, 5, use_pallas="on")       # pads 40 -> tile
    assert padded.num_items > 40
    import jax.numpy as jnp

    C = np.zeros((padded.num_items, padded.num_items), np.int32)
    C[:40, :40] = rng.integers(0, 9, (40, 40))
    padded.C = jnp.asarray(C)
    padded.row_sums = jnp.asarray(C.sum(axis=1).astype(np.int32))
    padded.observed = int(C.sum())
    st = padded.checkpoint_state()

    plain = DeviceScorer(40, 5, use_pallas="off")
    plain.restore_state(st)                              # slice down
    np.testing.assert_array_equal(np.asarray(plain.C), C[:40, :40])
    assert plain.observed == padded.observed

    st2 = plain.checkpoint_state()
    padded2 = DeviceScorer(40, 5, use_pallas="on")
    padded2.restore_state(st2)                           # zero-extend
    np.testing.assert_array_equal(np.asarray(padded2.C), C)


def test_restore_rejects_out_of_capacity_counts(tmp_path):
    from tpu_cooccurrence.ops.device_scorer import DeviceScorer
    import jax.numpy as jnp
    import pytest

    big = DeviceScorer(64, 5, use_pallas="off")
    C = np.zeros((64, 64), np.int32)
    C[50, 50] = 3                                        # beyond capacity 40
    big.C = jnp.asarray(C)
    big.row_sums = jnp.asarray(C.sum(axis=1).astype(np.int32))
    st = big.checkpoint_state()

    small = DeviceScorer(40, 5, use_pallas="off")
    with pytest.raises(ValueError, match="capacity"):
        small.restore_state(st)


def test_restore_ignores_stale_meta_sidecar(tmp_path):
    """The npz is the atomic commit point: restore must not read the
    meta.json sidecar (which can lag by a crash between the two writes)."""
    users, items, ts = random_stream(25, n=300)
    cfg = make_cfg(tmp_path)
    a = CooccurrenceJob(cfg)
    a.add_batch(users, items, ts)
    a.checkpoint()
    # Corrupt the sidecar as a crash between the npz and meta writes would.
    (tmp_path / "ckpt" / "meta.json").write_text('{"seed": 999}')

    b = CooccurrenceJob(make_cfg(tmp_path))
    b.restore()  # must succeed, using the meta embedded in the npz
    assert b.windows_fired == a.windows_fired


def test_restore_across_count_dtype(tmp_path):
    """int16 checkpoints widen to int32 freely; narrowing is bounds-checked."""
    import jax.numpy as jnp
    import pytest

    from tpu_cooccurrence.ops.device_scorer import DeviceScorer

    s16 = DeviceScorer(32, 5, count_dtype="int16")
    C = np.zeros((32, 32), np.int16)
    C[3, 4] = 1000
    s16.C = jnp.asarray(C)
    s16.row_sums = jnp.asarray(C.sum(axis=1).astype(np.int32))
    st = s16.checkpoint_state()

    s32 = DeviceScorer(32, 5, count_dtype="int32")
    s32.restore_state(st)
    assert np.asarray(s32.C).dtype == np.int32
    assert int(np.asarray(s32.C)[3, 4]) == 1000

    big = DeviceScorer(32, 5, count_dtype="int32")
    C2 = np.zeros((32, 32), np.int32)
    C2[1, 1] = 70_000  # beyond int16
    big.C = jnp.asarray(C2)
    big.row_sums = jnp.asarray(C2.sum(axis=1).astype(np.int32))
    st2 = big.checkpoint_state()
    with pytest.raises(ValueError, match="int16"):
        DeviceScorer(32, 5, count_dtype="int16").restore_state(st2)


def test_deferred_resume_keeps_real_emission_count(tmp_path):
    """Defer-to-defer resume restores the real emission count; only a
    per-window-backend resume takes the rescored-rows substitution."""
    from tpu_cooccurrence.config import Backend, Config
    from tpu_cooccurrence.job import CooccurrenceJob
    from tpu_cooccurrence.metrics import RESCORED_ITEMS
    from test_pipeline import random_stream

    kw = dict(window_size=10, seed=11, item_cut=5, user_cut=3,
              backend=Backend.SPARSE, checkpoint_dir=str(tmp_path / "ck"))
    users, items, ts = random_stream(71, n=600)
    a = CooccurrenceJob(Config(**kw))
    assert a.scorer.defer_results
    a.add_batch(users, items, ts)
    a.checkpoint()
    rescored = a.counters.get(RESCORED_ITEMS)
    real = a.emissions
    assert rescored > real  # rows rescored across windows, drained once

    b = CooccurrenceJob(Config(**kw))          # deferred again
    b.restore()
    assert b.emissions == real

    c = CooccurrenceJob(Config(**kw, emit_updates=True))  # per-window
    c.restore()
    assert c.emissions == rescored


# -- generations, integrity, quarantine (robustness PR) ----------------


def test_generations_number_retain_and_latest(tmp_path):
    """Each save commits a new state.<gen>.npz, LATEST tracks the
    newest, and retention keeps only --checkpoint-retain generations."""
    users, items, ts = random_stream(30, n=400)
    cfg = make_cfg(tmp_path, checkpoint_retain=2)
    job = CooccurrenceJob(cfg)
    step = len(users) // 4
    for i in range(4):
        job.add_batch(users[i * step:(i + 1) * step],
                      items[i * step:(i + 1) * step],
                      ts[i * step:(i + 1) * step])
        job.checkpoint()
    ck = tmp_path / "ckpt"
    gens = sorted(int(p.name.split(".")[1]) for p in ck.glob("state.*.npz"))
    assert gens == [3, 4], f"retention should keep newest 2, got {gens}"
    assert (ck / "LATEST").read_text().strip() == "state.4.npz"

    b = CooccurrenceJob(make_cfg(tmp_path, checkpoint_retain=2))
    b.restore()
    assert b.windows_fired == job.windows_fired


def test_exists_with_generation_files(tmp_path):
    """exists() sees generation-numbered files, the legacy un-numbered
    file, and nothing when only foreign/quarantined files remain."""
    from tpu_cooccurrence.state import checkpoint as ckpt

    users, items, ts = random_stream(31, n=200)
    job = CooccurrenceJob(make_cfg(tmp_path))
    ck = tmp_path / "ckpt"
    assert not ckpt.exists(job, str(ck))
    job.add_batch(users, items, ts)
    job.checkpoint()
    assert ckpt.exists(job, str(ck))
    # Generation file renamed away (e.g. quarantined): nothing restorable.
    for p in ck.glob("state.*.npz"):
        p.rename(str(p) + ".corrupt")
    assert not ckpt.exists(job, str(ck))
    # Legacy un-numbered file alone counts (gen 0 compatibility).
    (ck / "state.npz").write_bytes(b"whatever")
    assert ckpt.exists(job, str(ck))


def test_corrupt_latest_falls_back_a_generation(tmp_path, caplog):
    """Truncating the newest generation must not crash-loop restore: it
    falls back to the previous generation, quarantines the bad file as
    *.corrupt, and counts it on the quarantine gauge."""
    import logging

    from tpu_cooccurrence.observability.registry import REGISTRY
    from tpu_cooccurrence.state.checkpoint import QUARANTINE_GAUGE

    users, items, ts = random_stream(32, n=400)
    job = CooccurrenceJob(make_cfg(tmp_path))
    half = 200
    job.add_batch(users[:half], items[:half], ts[:half])
    job.checkpoint()
    fired_at_gen1 = job.windows_fired
    job.add_batch(users[half:], items[half:], ts[half:])
    job.checkpoint()
    ck = tmp_path / "ckpt"
    latest = max(ck.glob("state.*.npz"),
                 key=lambda p: int(p.name.split(".")[1]))
    # Tear the newest snapshot as a mid-write power loss would.
    with open(latest, "r+b") as f:
        f.truncate(latest.stat().st_size // 2)

    before = REGISTRY.gauge(QUARANTINE_GAUGE).get()
    b = CooccurrenceJob(make_cfg(tmp_path))
    with caplog.at_level(logging.ERROR, "tpu_cooccurrence.checkpoint"):
        b.restore()
    assert b.windows_fired == fired_at_gen1  # the older generation
    assert (ck / (latest.name + ".corrupt")).exists()
    assert not latest.exists()
    assert REGISTRY.gauge(QUARANTINE_GAUGE).get() == before + 1
    assert any("quarantined" in r.message for r in caplog.records)


def test_digest_mismatch_detected_without_truncation(tmp_path):
    """A bit-flip that keeps the zip container readable still fails the
    sha256 verification (np.load alone would restore silently)."""
    import numpy as np

    from tpu_cooccurrence.state.checkpoint import (
        CheckpointCorrupt, _load_verified, compute_digest)

    good = {"a": np.arange(10), "b": np.ones(3)}
    path = tmp_path / "state.1.npz"
    arrays = dict(good)
    arrays["digest_sha256"] = np.frombuffer(
        compute_digest(good).encode(), dtype=np.uint8)
    np.savez(path, **arrays)
    assert _load_verified(str(path))  # intact file verifies

    tampered = dict(good)
    tampered["a"] = np.arange(10) + 1  # the bit-flip
    tampered["digest_sha256"] = arrays["digest_sha256"]
    np.savez(path, **tampered)
    with pytest.raises(CheckpointCorrupt, match="digest mismatch"):
        _load_verified(str(path))


def test_all_generations_corrupt_raises(tmp_path):
    from tpu_cooccurrence.state.checkpoint import CheckpointCorrupt

    users, items, ts = random_stream(33, n=200)
    job = CooccurrenceJob(make_cfg(tmp_path))
    job.add_batch(users, items, ts)
    job.checkpoint()
    ck = tmp_path / "ckpt"
    for p in ck.glob("state.*.npz"):
        with open(p, "r+b") as f:
            f.truncate(16)
    b = CooccurrenceJob(make_cfg(tmp_path))
    with pytest.raises(CheckpointCorrupt, match="no checkpoint generation"):
        b.restore()


def test_step_back_retires_newest_generation(tmp_path):
    from tpu_cooccurrence.state.checkpoint import step_back

    users, items, ts = random_stream(34, n=300)
    job = CooccurrenceJob(make_cfg(tmp_path))
    half = 150
    job.add_batch(users[:half], items[:half], ts[:half])
    job.checkpoint()
    fired_gen1 = job.windows_fired
    job.add_batch(users[half:], items[half:], ts[half:])
    job.checkpoint()
    ck = tmp_path / "ckpt"

    assert step_back(str(ck)) == 2
    assert (ck / "state.2.npz.rolledback").exists()
    b = CooccurrenceJob(make_cfg(tmp_path))
    b.restore()
    assert b.windows_fired == fired_gen1
    # Only one generation left: nothing to step back to.
    assert step_back(str(ck)) is None


def test_save_sweeps_orphaned_tmps(tmp_path):
    """A crash between mkstemp and os.replace leaves a *.tmp behind;
    the next save deletes it once it is old enough to be provably dead,
    and leaves fresh ones (a live concurrent writer's) alone."""
    import os as _os
    import time as _time

    users, items, ts = random_stream(35, n=200)
    job = CooccurrenceJob(make_cfg(tmp_path))
    job.add_batch(users[:100], items[:100], ts[:100])
    job.checkpoint()
    ck = tmp_path / "ckpt"
    stale = ck / "deadbeef.tmp"
    stale.write_bytes(b"orphan")
    old = _time.time() - 3600
    _os.utime(stale, (old, old))
    fresh = ck / "cafef00d.tmp"
    fresh.write_bytes(b"live writer")
    job.add_batch(users[100:], items[100:], ts[100:])
    job.checkpoint()
    assert not stale.exists(), "aged orphan tmp must be swept"
    assert fresh.exists(), "fresh tmp may belong to a live writer"


def test_restore_missing_checkpoint_message(tmp_path):
    job = CooccurrenceJob(make_cfg(tmp_path))
    with pytest.raises(FileNotFoundError, match="no checkpoint"):
        job.restore()


def test_restore_legacy_without_meta_json_message(tmp_path):
    """A pre-atomic-commit npz (no embedded meta_json) is a format
    error, not corruption: explicit message, no quarantine."""
    import numpy as np

    users, items, ts = random_stream(36, n=100)
    job = CooccurrenceJob(make_cfg(tmp_path))
    ck = tmp_path / "ckpt"
    ck.mkdir()
    np.savez(ck / "state.npz", item_vocab=np.arange(3))
    with pytest.raises(ValueError, match="no embedded\\s+meta_json"):
        job.restore()
    assert (ck / "state.npz").exists(), "format errors must not quarantine"


def test_config_mismatch_not_quarantined(tmp_path):
    """An operator restoring with the wrong flags gets the mismatch
    message; the (perfectly good) checkpoint stays in place."""
    users, items, ts = random_stream(37, n=150)
    a = CooccurrenceJob(make_cfg(tmp_path))
    a.add_batch(users, items, ts)
    a.checkpoint()
    bad = CooccurrenceJob(make_cfg(tmp_path, item_cut=99))
    with pytest.raises(ValueError, match="config mismatch for item_cut"):
        bad.restore()
    ck = tmp_path / "ckpt"
    assert list(ck.glob("state.*.npz")), "mismatch must not quarantine"
    assert not list(ck.glob("*.corrupt"))


def test_legacy_unnumbered_checkpoint_still_restores(tmp_path):
    """A state.npz written by the pre-generation format restores as
    generation 0 (rolling-upgrade compatibility)."""
    import os as _os

    users, items, ts = random_stream(38, n=300)
    a = CooccurrenceJob(make_cfg(tmp_path))
    a.add_batch(users, items, ts)
    a.checkpoint()
    ck = tmp_path / "ckpt"
    gen1 = ck / "state.1.npz"
    _os.replace(gen1, ck / "state.npz")  # demote to the legacy name
    (ck / "LATEST").unlink()
    b = CooccurrenceJob(make_cfg(tmp_path))
    b.restore()
    assert b.windows_fired == a.windows_fired


# -- epoch-commit plane (multi-host gang contract, ISSUE 10) -----------


def _fake_gen(d, suffix, gen, marker):
    import os

    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, f"state{suffix}.{gen}.npz"), "wb") as f:
        f.write(b"x")
    if marker:
        open(os.path.join(d, f"EPOCH{suffix}.{gen}"), "w").close()


def test_epoch_markers_and_committed_generations(tmp_path):
    from tpu_cooccurrence.state import checkpoint as ckpt

    d = str(tmp_path / "ck")
    _fake_gen(d, ".p0", 1, marker=True)
    _fake_gen(d, ".p0", 2, marker=True)
    _fake_gen(d, ".p0", 3, marker=False)  # crashed pre-commit
    _fake_gen(d, ".p1", 1, marker=True)   # other suffix: independent
    assert ckpt.epoch_markers(d, ".p0") == [2, 1]
    committed = ckpt.committed_generations(d, ".p0")
    assert [g for g, _ in committed] == [2, 1]
    assert ckpt.newest_committed(d, ".p0") == 2
    assert ckpt.newest_committed(d, ".p1") == 1
    assert ckpt.newest_committed(d, ".p9") == -1


def test_committed_generations_legacy_no_markers(tmp_path, caplog):
    """A pre-epoch directory (generations, zero markers) keeps
    restoring — with a warning, not a veto."""
    import logging

    from tpu_cooccurrence.state import checkpoint as ckpt

    d = str(tmp_path / "ck")
    _fake_gen(d, ".p0", 1, marker=False)
    _fake_gen(d, ".p0", 2, marker=False)
    with caplog.at_level(logging.WARNING,
                         logger="tpu_cooccurrence.checkpoint"):
        committed = ckpt.committed_generations(d, ".p0")
    assert [g for g, _ in committed] == [2, 1]
    assert any("no EPOCH markers" in r.message for r in caplog.records)


def test_quarantine_uncommitted_moves_files_and_markers(tmp_path):
    import os

    from tpu_cooccurrence.state import checkpoint as ckpt

    d = str(tmp_path / "ck")
    _fake_gen(d, ".p0", 1, marker=True)
    _fake_gen(d, ".p0", 2, marker=True)   # committed here, not gang-wide
    _fake_gen(d, ".p0", 3, marker=False)
    assert ckpt.quarantine_uncommitted(d, ".p0", above_gen=1) == [3, 2]
    assert sorted(p for p in os.listdir(d) if p.endswith(".partial")) \
        == ["state.p0.2.npz.partial", "state.p0.3.npz.partial"]
    # Markers of quarantined generations are dropped too.
    assert ckpt.epoch_markers(d, ".p0") == [1]
    # Idempotent: a second vote on the same state moves nothing.
    assert ckpt.quarantine_uncommitted(d, ".p0", above_gen=1) == []


def test_save_writes_no_epoch_markers_single_process(tmp_path):
    """Single-process saves (empty suffix) write no epoch plane at all:
    restore semantics are exactly the pre-gang ones."""
    users, items, ts = random_stream(33, n=200)
    job = CooccurrenceJob(make_cfg(tmp_path))
    job.add_batch(users, items, ts)
    job.checkpoint()
    assert not [p for p in (tmp_path / "ckpt").iterdir()
                if p.name.startswith("EPOCH")]


def test_partial_quarantine_ages_out_with_retention(tmp_path):
    """*.partial fallout ages out of the retain window exactly like
    *.corrupt (the PR-9 sweep, extended)."""
    users, items, ts = random_stream(34, n=400)
    cfg = make_cfg(tmp_path, checkpoint_retain=2)
    job = CooccurrenceJob(cfg)
    ck = tmp_path / "ckpt"
    ck.mkdir(exist_ok=True)
    # A quarantined partial from a long-dead generation.
    (ck / "state.1.npz.partial").write_bytes(b"x")
    step = len(users) // 4
    for i in range(4):
        job.add_batch(users[i * step:(i + 1) * step],
                      items[i * step:(i + 1) * step],
                      ts[i * step:(i + 1) * step])
        job.checkpoint()
    # Retention window is generations {3, 4}: the gen-1 partial aged out.
    assert not (ck / "state.1.npz.partial").exists()


def test_ckpt_commit_site_fires_with_generation_seq():
    """The ckpt_commit chaos site addresses the torn-pointer window by
    GENERATION (not window ordinal): a spec for generation 2 must not
    fire at the generation-1 commit."""
    from tpu_cooccurrence.robustness.faults import FaultPlan

    plan = FaultPlan.parse(["ckpt_commit:2:exception"])
    plan.fire("ckpt_commit", seq=1)
    assert not plan.specs[0].fired
    import pytest as _pytest

    from tpu_cooccurrence.robustness.faults import InjectedFault

    with _pytest.raises(InjectedFault):
        plan.fire("ckpt_commit", seq=2)
