"""Checkpoint/resume: a restored job must continue bit-identically.

Closes the reference's fault-tolerance gap (SURVEY §5): rescorer state,
reservoirs, window buffers, and the source offset all survive."""

import numpy as np

from tpu_cooccurrence.config import Backend, Config
from tpu_cooccurrence.io.source import FileMonitorSource
from tpu_cooccurrence.job import CooccurrenceJob

from test_pipeline import assert_latest_equal, random_stream


def make_cfg(tmp_path, backend=Backend.ORACLE, **kw):
    kw.setdefault("window_size", 10)
    kw.setdefault("seed", 0xABCD)
    kw.setdefault("item_cut", 5)
    kw.setdefault("user_cut", 3)
    kw.setdefault("development_mode", True)
    if backend != Backend.ORACLE:
        kw.setdefault("num_items", 32)
    return Config(backend=backend, checkpoint_dir=str(tmp_path / "ckpt"), **kw)


def test_resume_equals_uninterrupted(tmp_path):
    users, items, ts = random_stream(21, n=500)
    half = 230  # mid-stream, mid-window

    # Uninterrupted run.
    ref = CooccurrenceJob(make_cfg(tmp_path))
    ref.add_batch(users, items, ts)
    ref.finish()

    # Run A: process half, checkpoint, abandon.
    a = CooccurrenceJob(make_cfg(tmp_path))
    a.add_batch(users[:half], items[:half], ts[:half])
    a.checkpoint()

    # Run B: fresh job, restore, continue.
    b = CooccurrenceJob(make_cfg(tmp_path))
    b.restore()
    b.add_batch(users[half:], items[half:], ts[half:])
    b.finish()

    assert_latest_equal(ref.latest, b.latest)
    assert ref.counters.as_dict() == b.counters.as_dict()
    assert ref.windows_fired == b.windows_fired


def test_resume_device_backend(tmp_path):
    users, items, ts = random_stream(22, n=400)
    half = 190

    ref = CooccurrenceJob(make_cfg(tmp_path, backend=Backend.DEVICE))
    ref.add_batch(users, items, ts)
    ref.finish()

    a = CooccurrenceJob(make_cfg(tmp_path, backend=Backend.DEVICE))
    a.add_batch(users[:half], items[:half], ts[:half])
    a.checkpoint()

    b = CooccurrenceJob(make_cfg(tmp_path, backend=Backend.DEVICE))
    b.restore()
    b.add_batch(users[half:], items[half:], ts[half:])
    b.finish()

    assert set(ref.latest) == set(b.latest)
    for item in ref.latest:
        np.testing.assert_allclose(
            np.array([v for _, v in b.latest[item]]),
            np.array([v for _, v in ref.latest[item]]), rtol=1e-6, atol=1e-6)


def test_config_mismatch_rejected(tmp_path):
    users, items, ts = random_stream(23, n=100)
    a = CooccurrenceJob(make_cfg(tmp_path))
    a.add_batch(users, items, ts)
    a.checkpoint()
    bad = CooccurrenceJob(make_cfg(tmp_path, user_cut=7))
    try:
        bad.restore()
    except ValueError as e:
        assert "user_cut" in str(e)
    else:
        raise AssertionError("expected config-mismatch ValueError")


def test_source_offset_survives(tmp_path):
    f = tmp_path / "in.csv"
    f.write_text("1,10,1\n1,11,2\n")
    cfg = make_cfg(tmp_path)
    job = CooccurrenceJob(cfg)
    src = FileMonitorSource(str(f), job.counters)
    lines = list(src.lines())
    assert len(lines) == 2
    job.checkpoint(source=src)

    job2 = CooccurrenceJob(make_cfg(tmp_path))
    src2 = FileMonitorSource(str(f), job2.counters)
    job2.restore(source=src2)
    # Same file, same mtime: already consumed -> no re-ingest.
    assert list(src2.lines()) == []


def test_periodic_checkpointing(tmp_path):
    cfg = make_cfg(tmp_path, checkpoint_every_windows=2)
    users, items, ts = random_stream(24, n=300)
    job = CooccurrenceJob(cfg)
    job.add_batch(users, items, ts)
    job.finish()
    assert (tmp_path / "ckpt" / "state.npz").exists()
    assert (tmp_path / "ckpt" / "meta.json").exists()


def test_restore_across_vocab_padding_change(tmp_path):
    """A checkpoint written with pallas vocab padding restores after the
    default flipped to the unpadded XLA path (and vice versa)."""
    from tpu_cooccurrence.ops.device_scorer import DeviceScorer

    rng = np.random.default_rng(5)
    padded = DeviceScorer(40, 5, use_pallas="on")       # pads 40 -> tile
    assert padded.num_items > 40
    import jax.numpy as jnp

    C = np.zeros((padded.num_items, padded.num_items), np.int32)
    C[:40, :40] = rng.integers(0, 9, (40, 40))
    padded.C = jnp.asarray(C)
    padded.row_sums = jnp.asarray(C.sum(axis=1).astype(np.int32))
    padded.observed = int(C.sum())
    st = padded.checkpoint_state()

    plain = DeviceScorer(40, 5, use_pallas="off")
    plain.restore_state(st)                              # slice down
    np.testing.assert_array_equal(np.asarray(plain.C), C[:40, :40])
    assert plain.observed == padded.observed

    st2 = plain.checkpoint_state()
    padded2 = DeviceScorer(40, 5, use_pallas="on")
    padded2.restore_state(st2)                           # zero-extend
    np.testing.assert_array_equal(np.asarray(padded2.C), C)


def test_restore_rejects_out_of_capacity_counts(tmp_path):
    from tpu_cooccurrence.ops.device_scorer import DeviceScorer
    import jax.numpy as jnp
    import pytest

    big = DeviceScorer(64, 5, use_pallas="off")
    C = np.zeros((64, 64), np.int32)
    C[50, 50] = 3                                        # beyond capacity 40
    big.C = jnp.asarray(C)
    big.row_sums = jnp.asarray(C.sum(axis=1).astype(np.int32))
    st = big.checkpoint_state()

    small = DeviceScorer(40, 5, use_pallas="off")
    with pytest.raises(ValueError, match="capacity"):
        small.restore_state(st)


def test_restore_ignores_stale_meta_sidecar(tmp_path):
    """The npz is the atomic commit point: restore must not read the
    meta.json sidecar (which can lag by a crash between the two writes)."""
    users, items, ts = random_stream(25, n=300)
    cfg = make_cfg(tmp_path)
    a = CooccurrenceJob(cfg)
    a.add_batch(users, items, ts)
    a.checkpoint()
    # Corrupt the sidecar as a crash between the npz and meta writes would.
    (tmp_path / "ckpt" / "meta.json").write_text('{"seed": 999}')

    b = CooccurrenceJob(make_cfg(tmp_path))
    b.restore()  # must succeed, using the meta embedded in the npz
    assert b.windows_fired == a.windows_fired


def test_restore_across_count_dtype(tmp_path):
    """int16 checkpoints widen to int32 freely; narrowing is bounds-checked."""
    import jax.numpy as jnp
    import pytest

    from tpu_cooccurrence.ops.device_scorer import DeviceScorer

    s16 = DeviceScorer(32, 5, count_dtype="int16")
    C = np.zeros((32, 32), np.int16)
    C[3, 4] = 1000
    s16.C = jnp.asarray(C)
    s16.row_sums = jnp.asarray(C.sum(axis=1).astype(np.int32))
    st = s16.checkpoint_state()

    s32 = DeviceScorer(32, 5, count_dtype="int32")
    s32.restore_state(st)
    assert np.asarray(s32.C).dtype == np.int32
    assert int(np.asarray(s32.C)[3, 4]) == 1000

    big = DeviceScorer(32, 5, count_dtype="int32")
    C2 = np.zeros((32, 32), np.int32)
    C2[1, 1] = 70_000  # beyond int16
    big.C = jnp.asarray(C2)
    big.row_sums = jnp.asarray(C2.sum(axis=1).astype(np.int32))
    st2 = big.checkpoint_state()
    with pytest.raises(ValueError, match="int16"):
        DeviceScorer(32, 5, count_dtype="int16").restore_state(st2)


def test_deferred_resume_keeps_real_emission_count(tmp_path):
    """Defer-to-defer resume restores the real emission count; only a
    per-window-backend resume takes the rescored-rows substitution."""
    from tpu_cooccurrence.config import Backend, Config
    from tpu_cooccurrence.job import CooccurrenceJob
    from tpu_cooccurrence.metrics import RESCORED_ITEMS
    from test_pipeline import random_stream

    kw = dict(window_size=10, seed=11, item_cut=5, user_cut=3,
              backend=Backend.SPARSE, checkpoint_dir=str(tmp_path / "ck"))
    users, items, ts = random_stream(71, n=600)
    a = CooccurrenceJob(Config(**kw))
    assert a.scorer.defer_results
    a.add_batch(users, items, ts)
    a.checkpoint()
    rescored = a.counters.get(RESCORED_ITEMS)
    real = a.emissions
    assert rescored > real  # rows rescored across windows, drained once

    b = CooccurrenceJob(Config(**kw))          # deferred again
    b.restore()
    assert b.emissions == real

    c = CooccurrenceJob(Config(**kw, emit_updates=True))  # per-window
    c.restore()
    assert c.emissions == rescored
