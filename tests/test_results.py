"""LatestResults store: array absorption, lazy mapping, compaction."""

import numpy as np

from tpu_cooccurrence.state.results import (LatestResults, TopKBatch,
                                            materialize_dense)
from tpu_cooccurrence.state.vocab import IdMap


def _vocab(n):
    v = IdMap()
    v.map_batch(np.arange(n, dtype=np.int64) * 10)  # external = dense*10
    return v


def _batch(rows, idx, vals):
    return TopKBatch(np.asarray(rows, np.int32),
                     np.asarray(idx, np.int32),
                     np.asarray(vals, np.float32))


def test_absorb_and_lazy_materialize():
    v = _vocab(8)
    lr = LatestResults(v)
    lr.absorb_batch(_batch([1, 3], [[2, 5], [0, 4]],
                           [[9.0, 7.0], [3.0, -np.inf]]))
    assert set(lr) == {10, 30}
    assert lr[10] == [(20, 9.0), (50, 7.0)]
    assert lr[30] == [(0, 3.0)]  # -inf slot filtered
    assert 10 in lr and 20 not in lr
    assert len(lr) == 2


def test_newer_batch_supersedes():
    v = _vocab(8)
    lr = LatestResults(v)
    lr.absorb_batch(_batch([1], [[2, 3]], [[5.0, 4.0]]))
    lr.absorb_batch(_batch([1, 2], [[4, 5], [6, 7]],
                           [[8.0, 6.0], [2.0, 1.0]]))
    assert lr[10] == [(40, 8.0), (50, 6.0)]
    assert lr[20] == [(60, 2.0), (70, 1.0)]


def test_pointer_growth_past_initial_capacity():
    n = 3000  # > the 1024 initial pointer table
    v = _vocab(n)
    lr = LatestResults(v)
    rows = np.arange(n, dtype=np.int32)
    idx = np.tile(np.array([[0, 1]], np.int32), (n, 1))
    vals = np.stack([np.arange(n, dtype=np.float32),
                     np.arange(n, dtype=np.float32) - 1], axis=1)
    lr.absorb_batch(TopKBatch(rows, idx, vals))
    assert len(lr) == n
    assert lr[(n - 1) * 10] == [(0, float(n - 1)), (10, float(n - 2))]


def test_list_rows_and_batches_mix():
    v = _vocab(8)
    lr = LatestResults(v)
    lr.set_row(1, [(2, 5.0)])
    lr.absorb_batch(_batch([2], [[3, 0]], [[4.0, -np.inf]]))
    lr.set_row(2, [(5, 1.0)])  # list row supersedes batch row
    assert lr[10] == [(20, 5.0)]
    assert lr[20] == [(50, 1.0)]


def test_compaction_preserves_live_rows():
    v = _vocab(64)
    lr = LatestResults(v)
    lr._COMPACT_MIN_ROWS = 8  # force compaction early
    for t in range(16):
        rows = [t % 4, 4 + t % 4]
        lr.absorb_batch(_batch(rows, [[1, 2], [3, 4]],
                               [[float(t), 1.0], [float(t), 0.5]]))
    assert len(lr) == 8
    for d in range(4):
        last = max(t for t in range(16) if t % 4 == d)
        assert lr[d * 10][0][1] == float(last)
    assert len(lr._batches) <= 3  # old superseded batches were dropped


def test_materialize_dense_passthrough_and_batch():
    out = [(3, [(1, 2.0)])]
    assert materialize_dense(out) == out
    b = _batch([5], [[7, 0]], [[1.5, -np.inf]])
    assert materialize_dense(b) == [(5, [(7, 1.5)])]
