"""Chaos soak: the exactly-once-output contract under injected faults.

The capstone of the robustness plane (ISSUE 3): run the *real* CLI
under the *real* supervisor with crashes injected at distinct hot-path
sites — window fire, scorer dispatch, checkpoint post-write-pre-rename
(a torn commit), journal append — and assert the total stdout is
bit-identical to an uninterrupted run. Every recovery layer is in the
loop: supervisor respawn, checkpoint-generation fallback past the torn
snapshot, journal torn-tail sealing, and (separately) the hang
watchdog killing a stalled child.

The quick variant is tier-1; the multi-site soak across pipeline
depths 0 and 2 is ``slow`` (full-suite / round-gate lane).
"""

import os
import subprocess
import sys

import pytest

from tpu_cooccurrence.supervisor import supervise

from test_cli import write_stream

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ENV = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")


class _Sink:
    def __init__(self):
        self.text = ""

    def write(self, s):
        self.text += s


def _clean_run(tmp_path, base_args):
    """The uninterrupted reference run (its own checkpoint dir)."""
    proc = subprocess.run(
        [sys.executable, "-m", "tpu_cooccurrence.cli"] + base_args
        + ["--checkpoint-dir", str(tmp_path / "ck-clean")],
        capture_output=True, text=True, env=ENV, cwd=REPO, timeout=600)
    assert proc.returncode == 0, proc.stderr[-800:]
    return proc.stdout


def _supervised_run(tmp_path, base_args, fault_specs, attempts,
                    watchdog_stale_after_s=None):
    """Drive supervise() in-process over real CLI children with the
    fault plan armed (exactly-once across restarts via the marker dir)."""
    ck = tmp_path / "ck"
    jpath = tmp_path / "journal.jsonl"
    cmd = [sys.executable, "-m", "tpu_cooccurrence.cli"] + base_args
    cmd += ["--checkpoint-dir", str(ck), "--journal", str(jpath),
            "--fault-state-dir", str(tmp_path / "fault-state")]
    for spec in fault_specs:
        cmd += ["--inject-fault", spec]
    sink = _Sink()
    rc = supervise(cmd, attempts=attempts, delay_s=0, stdout=sink,
                   journal_path=str(jpath), crash_loop_threshold=0,
                   watchdog_stale_after_s=watchdog_stale_after_s,
                   checkpoint_dir=str(ck))
    return rc, sink.text


def _assert_all_fired(tmp_path, n):
    fired = sorted(os.listdir(tmp_path / "fault-state"))
    assert len(fired) == n, (
        f"expected {n} injected faults to have fired, got {fired}")


def test_chaos_quick_crash_parity(tmp_path):
    """Tier-1 variant: three distinct crash sites — a window-loop crash,
    a torn checkpoint commit (post-write-pre-rename), and a crash at
    journal append — at pipeline depth 0; stdout must be bit-identical
    to the uninterrupted run, with zero operator action."""
    f = tmp_path / "in.csv"
    write_stream(f, n=600)
    base = ["-i", str(f), "-ws", "40", "-ic", "8", "-uc", "5",
            "-s", "0xC0FFEE", "--backend", "oracle",
            "--checkpoint-every-windows", "3",
            # Wide retain window: the PR-9 sweep ages out *.corrupt
            # files whose generation leaves the window, and this test's
            # final assertion wants the torn generation's forensics
            # still on disk (the sweep itself is pinned by
            # tests/test_state_store.py).
            "--checkpoint-retain", "10"]
    clean = _clean_run(tmp_path, base)
    assert clean, "reference run produced no output"

    rc, out = _supervised_run(
        tmp_path, base,
        ["window_fire:4:crash",
         "checkpoint_post_write:6:torn_write",
         "journal_append:9:crash"],
        attempts=4)
    assert rc == 0
    assert out == clean
    _assert_all_fired(tmp_path, 3)
    # The torn checkpoint commit really was quarantined on fallback.
    corrupt = [p for p in os.listdir(tmp_path / "ck")
               if p.endswith(".corrupt")]
    assert corrupt, "torn snapshot should have been quarantined"


def test_chaos_watchdog_hang_recovery_parity(tmp_path):
    """A child stalled by delay_ms injection past the watchdog
    threshold is killed, restarted, and the run completes with exact
    output parity — a hang costs one attempt, not the whole run."""
    f = tmp_path / "in.csv"
    write_stream(f, n=600)
    base = ["-i", str(f), "-ws", "40", "-ic", "8", "-uc", "5",
            "-s", "0xBEEF", "--backend", "oracle",
            "--checkpoint-every-windows", "3"]
    clean = _clean_run(tmp_path, base)

    rc, out = _supervised_run(
        tmp_path, base, ["window_fire:5:delay_ms:600000"],
        attempts=2, watchdog_stale_after_s=2.0)
    assert rc == 0
    assert out == clean
    _assert_all_fired(tmp_path, 1)


def test_chaos_exception_kind_recovers_too(tmp_path):
    """The exception kind (clean unwind, not SIGKILL) exits nonzero
    through normal error handling and the supervised run still
    converges to bit-identical output."""
    f = tmp_path / "in.csv"
    write_stream(f, n=400)
    base = ["-i", str(f), "-ws", "50", "-ic", "8", "-uc", "5",
            "-s", "0xFEED", "--backend", "oracle",
            "--checkpoint-every-windows", "2"]
    clean = _clean_run(tmp_path, base)
    rc, out = _supervised_run(
        tmp_path, base, ["scorer_dispatch:3:exception"], attempts=2)
    assert rc == 0
    assert out == clean
    _assert_all_fired(tmp_path, 1)


def test_chaos_scorer_breaker_trips_and_run_completes_on_fallback(tmp_path):
    """Graceful-degradation capstone (ISSUE 5): an injected dispatch
    failure inside the device scorer trips the circuit breaker mid-run;
    the run completes on the host-oracle fallback WITHOUT a supervisor
    or restart — degrade, don't die — and the trip is visible in the
    journal's ``breaker_state`` field."""
    f = tmp_path / "in.csv"
    write_stream(f, n=600)
    jpath = tmp_path / "journal.jsonl"
    proc = subprocess.run(
        [sys.executable, "-m", "tpu_cooccurrence.cli", "-i", str(f),
         "-ws", "40", "-ic", "8", "-uc", "5", "-s", "0xC0FFEE",
         "--backend", "device", "--journal", str(jpath),
         "--scorer-breaker-threshold", "1",
         "--scorer-breaker-probe-windows", "3",
         "--inject-fault", "scorer_breaker:3:exception"],
        capture_output=True, text=True, env=ENV, cwd=REPO, timeout=600)
    assert proc.returncode == 0, proc.stderr[-800:]
    assert proc.stdout, "run completed but emitted no results"
    from tpu_cooccurrence.observability.journal import read_records

    states = [r["breaker_state"] for r in read_records(str(jpath))]
    assert "open" in states, states          # the trip is journaled
    assert states[0] == "closed"             # and it happened mid-run
    assert states[-1] == "closed", states    # half-open probe recovered


def _run_cli(args, timeout=600, expect_rc=0):
    proc = subprocess.run(
        [sys.executable, "-m", "tpu_cooccurrence.cli"] + args,
        capture_output=True, text=True, env=ENV, cwd=REPO, timeout=timeout)
    if expect_rc is not None:
        assert proc.returncode == expect_rc, proc.stderr[-800:]
    return proc


@pytest.mark.parametrize("n_from,n_to,depth", [(2, 4, 0), (4, 2, 2)])
def test_chaos_rescale_kill_and_resume_other_topology(tmp_path, n_from,
                                                      n_to, depth):
    """Elastic-state capstone (ISSUE 9): kill a sharded-sparse run at
    ``--num-shards N`` mid-stream, resume at M — stdout bit-identical
    to resuming at N (the same-topology resume is the canonical
    reference: any restore rebuilds rows in key order, so rescale must
    change NOTHING beyond topology), both directions, depths 0 and 2.
    """
    f = tmp_path / "in.csv"
    write_stream(f, n=500)
    ck = tmp_path / "ck"

    def args(shards, extra=()):
        return ["-i", str(f), "-ws", "40", "-ic", "8", "-uc", "5",
                "-s", "0xC0FFEE", "--backend", "sparse",
                "--num-shards", str(shards),
                "--pipeline-depth", str(depth),
                "--checkpoint-every-windows", "3",
                "--checkpoint-dir", str(ck)] + list(extra)

    # Kill at N: the injected crash leaves a committed checkpoint behind
    # (rc != 0 — the crash is a SIGKILL-style exit, not a clean run).
    proc = _run_cli(args(n_from, ["--inject-fault", "window_fire:7:crash",
                                  "--fault-state-dir",
                                  str(tmp_path / "fault-state")]),
                    expect_rc=None)
    assert proc.returncode != 0
    assert not proc.stdout, "final dump must not have run before the kill"
    assert any(p.startswith("state") for p in os.listdir(ck)), \
        "no checkpoint to rescale from"
    import shutil

    shutil.copytree(ck, tmp_path / "ck-same")
    same_args = args(n_from)
    same_args[same_args.index(str(ck))] = str(tmp_path / "ck-same")

    # Resume at N (reference) and at M (rescaled) from the same kill.
    same = _run_cli(same_args)
    rescaled = _run_cli(args(n_to))
    assert same.stdout, "resumed run emitted nothing"
    assert "restored checkpoint" in rescaled.stderr
    assert rescaled.stdout == same.stdout
    _assert_all_fired(tmp_path, 1)


@pytest.mark.parametrize("depth", [0, 2])
def test_chaos_spill_enabled_stdout_identical_to_off(tmp_path, depth):
    """Tiered-state transparency through the real CLI: a spill-enabled
    sparse run's total stdout is bit-identical to spill-off on the same
    stream (spill/promote is exact movement, tie order included), at
    pipeline depths 0 and 2."""
    f = tmp_path / "in.csv"
    write_stream(f, n=450)
    base = ["-i", str(f), "-ws", "40", "-ic", "8", "-uc", "5",
            "-s", "0xC0FFEE", "--backend", "sparse",
            "--pipeline-depth", str(depth)]
    off = _run_cli(base)
    on = _run_cli(base + ["--spill-threshold-windows", "2",
                          "--spill-target-hbm-frac", "0.0"])
    assert off.stdout, "spill-off run emitted nothing"
    assert on.stdout == off.stdout
    assert "tiered state armed" in on.stderr


@pytest.mark.slow
@pytest.mark.parametrize("depth", [0, 2])
def test_chaos_soak_multi_site_parity(tmp_path, depth):
    """The full soak: crashes at four distinct sites (source read,
    window fire, torn checkpoint commit, journal append) plus a worker-
    thread crash at scorer dispatch, across pipeline depths 0 and 2 —
    total stdout bit-identical to the uninterrupted run at the same
    depth."""
    f = tmp_path / "in.csv"
    write_stream(f, n=4000)
    base = ["-i", str(f), "-ws", "150", "-ic", "8", "-uc", "5",
            "-s", "0xC0FFEE", "--backend", "oracle",
            "--pipeline-depth", str(depth),
            "--checkpoint-every-windows", "3",
            # Wide enough that the torn generation's *.corrupt survives
            # the PR-9 aged-quarantine sweep until the final assertion.
            "--checkpoint-retain", "12"]
    clean = _clean_run(tmp_path, base)
    faults = [
        "source_read:crash",                    # before any progress
        "window_fire:5:crash",
        "scorer_dispatch:9:crash",              # worker thread at depth 2
        "checkpoint_post_write:12:torn_write",  # corrupt committed latest
        "journal_append:15:crash",
    ]
    rc, out = _supervised_run(tmp_path, base, faults, attempts=7)
    assert rc == 0
    assert out == clean
    _assert_all_fired(tmp_path, len(faults))
    corrupt = [p for p in os.listdir(tmp_path / "ck")
               if p.endswith(".corrupt")]
    assert corrupt, "torn snapshot should have been quarantined"

    # Journal integrity across five kills: every surviving record
    # validates, ordinals are gapless, and any window journaled by
    # multiple attempts carries identical logical fields (the replay-
    # determinism contract).
    from tpu_cooccurrence.observability.journal import (read_records,
                                                        validate_record)

    recs = list(read_records(str(tmp_path / "journal.jsonl")))
    assert recs, "journal never written"
    by_seq = {}
    for r in recs:
        validate_record(r)
        logical = (r["ts"], r["events"], r["pairs"])
        assert by_seq.setdefault(r["seq"], logical) == logical
    assert max(by_seq) == len(by_seq), "window ordinals must be gapless"


def test_chaos_ckpt_commit_crash_in_torn_pointer_window(tmp_path):
    """ISSUE-10 durability satellite: crash INSIDE the torn-pointer
    window — generation file renamed into place but the directory
    entry not yet fsynced (the ckpt_commit site sits exactly between
    the rename and the directory fsync). The supervised restart must
    restore and converge to bit-identical output; the site's seq is
    the GENERATION number, so the spec pins the generation-2 commit."""
    f = tmp_path / "in.csv"
    write_stream(f, n=600)
    base = ["-i", str(f), "-ws", "40", "-ic", "8", "-uc", "5",
            "-s", "0xD1CE", "--backend", "oracle",
            "--checkpoint-every-windows", "3"]
    clean = _clean_run(tmp_path, base)
    rc, out = _supervised_run(
        tmp_path, base, ["ckpt_commit:2:crash"], attempts=2)
    assert rc == 0
    assert out == clean
    _assert_all_fired(tmp_path, 1)
