"""Compressed wire/checkpoint codecs (state/wire.py): exact round trips,
device-decode parity, and the cooclint rules that guard them.

Every encoder/decoder pair is exercised here by name — the
``wire-codec-roundtrip`` rule counts these references as the round-trip
evidence a codec needs to exist at all.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from tpu_cooccurrence.state.wire import (
    SENT, cell_promote_threshold, checked_narrow, checkpoint_codec,
    decode_sorted_u64, decode_update, decode_update_host, decode_varint,
    encode_sorted_u64, encode_update, encode_varint, pack_bits,
    packed_nbytes, resolve_cell_dtype, resolve_wire_format, unpack_bits)


# -- bit packing -------------------------------------------------------


@pytest.mark.parametrize("width", [1, 2, 7, 13, 17, 24, 31, 32])
def test_pack_bits_roundtrip(width):
    rng = np.random.default_rng(width)
    for n in (0, 1, 2, 63, 64, 65, 1000):
        hi = np.uint64(1) << np.uint64(width)
        vals = rng.integers(0, int(hi), n, dtype=np.uint64)
        if n:
            vals[0] = hi - np.uint64(1)  # max value must survive
            vals[-1] = 0
        words = pack_bits(vals, width)
        assert words.dtype == np.uint32
        assert len(words) == (n * width + 31) // 32
        np.testing.assert_array_equal(unpack_bits(words, width, n), vals)


def test_pack_bits_rejects_bad_inputs():
    with pytest.raises(ValueError, match="width"):
        pack_bits(np.zeros(1, np.uint64), 0)
    with pytest.raises(ValueError, match="width"):
        pack_bits(np.zeros(1, np.uint64), 33)
    with pytest.raises(ValueError, match="fit"):
        pack_bits(np.asarray([4], np.uint64), 2)


# -- varint ------------------------------------------------------------


def test_varint_roundtrip():
    rng = np.random.default_rng(0)
    for n in (0, 1, 500):
        vals = rng.integers(0, 2**62, n, dtype=np.uint64)
        if n:
            vals[0] = 0
            vals[-1] = np.uint64(2**62)
        buf = encode_varint(vals)
        np.testing.assert_array_equal(decode_varint(buf, n), vals)
    with pytest.raises(ValueError, match="nonnegative"):
        encode_varint(np.asarray([-1], np.int64))
    with pytest.raises(ValueError, match="expected"):
        decode_varint(encode_varint(np.asarray([1, 2], np.uint64)), 3)


def test_zigzag_varint_roundtrip():
    """Signed int64 round trip over the full domain — the delta-log
    columns (cell counts, external ids) ride this codec."""
    from tpu_cooccurrence.state.wire import (decode_zigzag_varint,
                                             encode_zigzag_varint)

    rng = np.random.default_rng(5)
    for n in (0, 1, 500):
        vals = rng.integers(-2**62, 2**62, n, dtype=np.int64)
        if n:
            vals[0] = np.iinfo(np.int64).min
            vals[-1] = np.iinfo(np.int64).max
        buf = encode_zigzag_varint(vals)
        np.testing.assert_array_equal(decode_zigzag_varint(buf, n), vals)
    # Small magnitudes stay small on the wire (the point of zigzag).
    assert len(encode_zigzag_varint(
        np.asarray([-1, 0, 1] * 100, np.int64))) == 300


def test_sorted_u64_roundtrip_and_compression():
    rng = np.random.default_rng(1)
    # Realistic cell keys (row << 32 | dst): tiny deltas within a row's
    # segment, one big jump per row boundary.
    rows = np.repeat(np.arange(200, dtype=np.int64), 100)
    dsts = rng.integers(0, 5000, 20000).astype(np.int64)
    keys = np.unique((rows << 32) | dsts)
    blob = encode_sorted_u64(keys)
    np.testing.assert_array_equal(decode_sorted_u64(blob, len(keys)), keys)
    # Sorted deltas must beat the raw 8 B/key layout by a wide margin.
    assert blob.nbytes * 2 < keys.nbytes
    with pytest.raises(ValueError, match="sorted"):
        encode_sorted_u64(np.asarray([5, 3], np.int64))
    assert len(encode_sorted_u64(np.zeros(0, np.int64))) == 0


# -- the packed update buffer ------------------------------------------


def _make_update(rng, n_new, n_d, n_rs, heap=1 << 18, items=5000):
    n = n_new + n_d + n_rs
    n_pad = 1 << max(6, int(np.ceil(np.log2(max(n, 1)))) + 1)
    upd = np.full((2, n_pad), SENT, dtype=np.int32)
    upd[1] = 0
    slots = rng.choice(heap, n_new + n_d, replace=False).astype(np.int32)
    upd[0, :n_new] = slots[:n_new]
    upd[1, :n_new] = rng.integers(0, items, n_new)
    upd[0, n_new:n_new + n_d] = slots[n_new:]
    upd[1, n_new:n_new + n_d] = rng.integers(-(2**31), 2**31, n_d)
    rows = rng.choice(items, n_rs, replace=False).astype(np.int32)
    upd[0, n_new + n_d:n] = rows
    upd[1, n_new + n_d:n] = rng.integers(-30000, 30000, n_rs)
    return upd, np.asarray([n_new, n_new + n_d], np.int32), n, n_pad


def _section_multiset(upd, lo, hi):
    return sorted(zip(upd[0, lo:hi].tolist(), upd[1, lo:hi].tolist()))


@pytest.mark.parametrize("shape", [
    (10, 300, 60), (0, 500, 90), (7, 0, 0), (0, 0, 0), (1, 1, 1),
    (0, 0, 40),
])
def test_encode_update_roundtrip_host(shape):
    rng = np.random.default_rng(hash(shape) & 0xFFFF)
    upd, bounds, n, n_pad = _make_update(rng, *shape)
    words_i, words_v, header = encode_update(upd, bounds, n)
    dec, dec_bounds = decode_update_host(words_i, words_v, header, n_pad)
    np.testing.assert_array_equal(dec_bounds, bounds)
    b0, b1 = int(bounds[0]), int(bounds[1])
    # Sections survive as multisets (the codec sorts within a section —
    # legal because every section's scatter is order-independent) and the
    # padding region is bit-identical to the raw buffer's.
    for lo, hi in ((0, b0), (b0, b1), (b1, n)):
        assert _section_multiset(dec, lo, hi) == _section_multiset(
            upd, lo, hi)
    np.testing.assert_array_equal(dec[:, n:], upd[:, n:])


def test_decode_update_jit_matches_host():
    """The device decode prologue is bit-identical to the host decoder
    (and therefore to the raw buffer modulo in-section order)."""
    rng = np.random.default_rng(9)
    for shape in ((25, 400, 80), (0, 64, 0), (3, 3, 3)):
        upd, bounds, n, n_pad = _make_update(rng, *shape)
        words_i, words_v, header = encode_update(upd, bounds, n)

        def pad(words):
            out = np.zeros(max(8, 2 * (len(words) + 1)), np.uint32)
            out[: len(words)] = words
            return out

        dec_host, b_host = decode_update_host(words_i, words_v, header,
                                              n_pad)
        dec_jit, b_jit = decode_update(jnp.asarray(pad(words_i)),
                                       jnp.asarray(pad(words_v)),
                                       jnp.asarray(header), n_pad)
        np.testing.assert_array_equal(np.asarray(dec_jit), dec_host)
        np.testing.assert_array_equal(np.asarray(b_jit), b_host)


def test_encode_update_compresses_realistic_window():
    """The acceptance yardstick at codec level: a realistic steady-state
    window (small deltas, sorted-ish slots) encodes to <= half the raw
    buffer's bytes."""
    rng = np.random.default_rng(3)
    upd, bounds, n, n_pad = _make_update(rng, 0, 20000, 4000)
    upd[1, :20000] = rng.integers(-5, 50, 20000)  # realistic deltas
    words_i, words_v, header = encode_update(upd, bounds, n)
    raw = upd.nbytes + bounds.nbytes
    assert packed_nbytes(words_i, words_v, header) * 2 <= raw


# -- narrow dtypes -----------------------------------------------------


def test_checked_narrow_guards():
    a = np.asarray([1, 32767], np.int64)
    assert checked_narrow(a, np.int16).dtype == np.int16
    with pytest.raises(OverflowError):
        checked_narrow(np.asarray([32768], np.int64), np.int16)
    with pytest.raises(OverflowError):
        checked_narrow(np.asarray([-129], np.int64), np.int8)
    assert checked_narrow(np.zeros(0, np.int64), np.int8).dtype == np.int8


def test_cell_promote_threshold():
    assert cell_promote_threshold("int32") is None
    assert cell_promote_threshold("int16") == 1 << 15
    assert cell_promote_threshold("int8") == 1 << 7


def test_flag_resolution():
    assert resolve_cell_dtype("auto", True) == "int16"
    assert resolve_cell_dtype("auto", False) == "int32"
    assert resolve_cell_dtype("int8", True) == "int8"
    assert resolve_wire_format("auto", True) == "packed"
    assert resolve_wire_format("auto", False) == "raw"
    assert checkpoint_codec("raw") == "raw"
    assert checkpoint_codec("auto") == "packed"
    assert checkpoint_codec("packed") == "packed"


# -- ledger accounting --------------------------------------------------


def test_ledger_encoded_and_basket_counters():
    """The raw/encoded uplink pair and the BasketBatch counter (PR-6
    packed uplink split out of the generic h2d totals)."""
    from tpu_cooccurrence.observability import TransferLedger

    led = TransferLedger()
    buf = np.zeros(256, np.uint32)
    led.up("plain", buf)
    led.up_encoded("update-packed", 8192, buf, buf)
    led.up_basket("fused-window", buf)
    snap = led.snapshot()
    assert snap["h2d_calls"] == 3
    assert snap["h2d_bytes"] == 4 * buf.nbytes
    assert snap["uplink_raw_bytes"] == 8192
    assert snap["uplink_enc_bytes"] == 2 * buf.nbytes
    assert snap["basket_h2d_bytes"] == buf.nbytes
    assert snap["basket_h2d_calls"] == 1
    led.reset()
    assert all(v == 0 for v in led.snapshot().values())


def test_fused_window_uplink_rides_basket_counter():
    """End to end: a --fused-window on run books its packed basket
    uploads on the basket counter, not just the generic totals."""
    from tpu_cooccurrence.config import Backend, Config
    from tpu_cooccurrence.job import CooccurrenceJob
    from tpu_cooccurrence.observability import LEDGER

    rng = np.random.default_rng(5)
    users = rng.integers(0, 30, 1500).astype(np.int64)
    items = rng.integers(0, 60, 1500).astype(np.int64)
    ts = np.cumsum(rng.integers(0, 2, 1500)).astype(np.int64)
    LEDGER.reset()
    cfg = Config(window_size=20, seed=3, item_cut=8, user_cut=6,
                 backend=Backend.DEVICE, fused_window="on")
    job = CooccurrenceJob(cfg)
    job.add_batch(users, items, ts)
    job.finish()
    snap = LEDGER.snapshot()
    assert snap["basket_h2d_calls"] > 0
    assert 0 < snap["basket_h2d_bytes"] <= snap["h2d_bytes"]
    LEDGER.reset()


# -- cooclint rules guarding this module --------------------------------


def test_wire_codec_rule_flags_missing_decoder():
    from tpu_cooccurrence.analysis import analyze_source

    bad = "def encode_thing(x):\n    return x\n"
    findings = analyze_source(bad, path="tpu_cooccurrence/state/wire.py",
                              rules=["wire-codec-roundtrip"])
    assert any("decode_thing" in f.message for f in findings)


def test_wire_codec_rule_requires_test_reference():
    from tpu_cooccurrence.analysis import analyze_source

    src = ("def encode_thing(x):\n    return x\n"
           "def decode_thing(x):\n    return x\n")
    findings = analyze_source(src, path="tpu_cooccurrence/state/wire.py",
                              rules=["wire-codec-roundtrip"])
    assert any("round-trip evidence" in f.message for f in findings)


def test_narrow_cast_rule():
    from tpu_cooccurrence.analysis import analyze_source

    bad = ("import numpy as np\n"
           "def f(a):\n"
           "    return a.astype(np.int16)\n")
    findings = analyze_source(bad, rules=["narrow-cast-guard"])
    assert findings and "guard" in findings[0].message
    guarded = ("import numpy as np\n"
               "def f(a):\n"
               "    if a.max() > 32767:\n"
               "        raise OverflowError\n"
               "    return a.astype(np.int16)\n")
    assert analyze_source(guarded, rules=["narrow-cast-guard"]) == []
    helper = ("from tpu_cooccurrence.state.wire import checked_narrow\n"
              "import numpy as np\n"
              "def f(a):\n"
              "    return checked_narrow(a, np.int16)\n")
    assert analyze_source(helper, rules=["narrow-cast-guard"]) == []
    sign_extend = ("import jax.numpy as jnp\n"
                   "def f(a):\n"
                   "    return a.astype(jnp.int16).astype(jnp.int32)\n")
    assert analyze_source(sign_extend, rules=["narrow-cast-guard"]) == []


def test_repo_is_clean_of_unguarded_narrow_casts():
    """The rules hold over the live tree (baseline-free, like
    rules_fused): run them through the real analyzer entry point."""
    import os

    from tpu_cooccurrence.analysis import Analyzer
    from tpu_cooccurrence.analysis.core import RULES

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    result = Analyzer(repo, rules=[RULES["narrow-cast-guard"],
                                   RULES["wire-codec-roundtrip"]]).run()
    assert result.findings == [], result.findings
