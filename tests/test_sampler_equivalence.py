"""Direct equivalence: vectorized sampler ops vs the record-at-a-time oracle.

Feeds identical window batches to ``ItemInteractionCut`` +
``UserReservoirSampler`` and to the OracleJob's internal operators, and
compares the *aggregated pair-delta matrices* (order-free) and all side
effects (histories, counters, feedback)."""

import numpy as np

from tpu_cooccurrence.config import Backend, Config
from tpu_cooccurrence.oracle import OracleJob
from tpu_cooccurrence.sampling.item_cut import ItemInteractionCut, grouped_rank
from tpu_cooccurrence.sampling.reservoir import UserReservoirSampler


def test_grouped_rank():
    np.testing.assert_array_equal(
        grouped_rank(np.array([5, 3, 5, 5, 3])), [0, 0, 1, 2, 1])
    np.testing.assert_array_equal(grouped_rank(np.array([], dtype=np.int64)), [])
    np.testing.assert_array_equal(grouped_rank(np.array([7])), [0])


def aggregate(pairs):
    agg = {}
    for s, d, v in zip(pairs.src.tolist(), pairs.dst.tolist(),
                       pairs.delta.tolist()):
        agg[(s, d)] = agg.get((s, d), 0) + v
    return {k: v for k, v in agg.items() if v != 0}


def test_sampler_matches_oracle_operators():
    rng = np.random.default_rng(0xFACE)
    cfg = Config(window_size=10, seed=99, item_cut=4, user_cut=3,
                 development_mode=True, backend=Backend.ORACLE)

    oracle = OracleJob(cfg)
    cut = ItemInteractionCut(cfg.item_cut, capacity=64)
    sampler = UserReservoirSampler(cfg.user_cut, cfg.seed, skip_cuts=False)

    for _window in range(30):
        n = int(rng.integers(1, 40))
        users = rng.integers(0, 8, n)
        items = rng.integers(0, 12, n)

        # Oracle path: drive the internal operators directly.
        interactions = [(int(u), int(i), 0) for u, i in zip(users, items)]
        tagged = oracle._item_cut_fire(interactions)
        o_pairs, o_rowsums, o_feedback = oracle._user_fire(tagged)
        for item, inc in o_feedback:
            oracle.item_interactions[item] += inc

        # Vectorized path.
        sampled = cut.fire(items.astype(np.int64))
        np.testing.assert_array_equal(
            sampled, [t[2] for t in tagged], err_msg="item-cut tags differ")
        pairs, feedback = sampler.fire(users.astype(np.int64),
                                       items.astype(np.int64), sampled)
        cut.apply_feedback(feedback)

        # Pair deltas: aggregated (i, j) -> count must match exactly.
        o_agg = {}
        for (i, j, inc) in o_pairs:
            o_agg[(i, j)] = o_agg.get((i, j), 0) + inc
        o_agg = {k: v for k, v in o_agg.items() if v != 0}
        assert aggregate(pairs) == o_agg

        # Row-sum derivation (segment-sum by src) must match the oracle's
        # explicitly-emitted row-sum deltas.
        o_rs = {}
        for (i, inc) in o_rowsums:
            o_rs[i] = o_rs.get(i, 0) + inc
        o_rs = {k: v for k, v in o_rs.items() if v != 0}
        v_rs = {}
        for s, v in zip(pairs.src.tolist(), pairs.delta.tolist()):
            v_rs[s] = v_rs.get(s, 0) + v
        v_rs = {k: v for k, v in v_rs.items() if v != 0}
        assert v_rs == o_rs

        # Feedback multiset must match.
        assert sorted(feedback.tolist()) == sorted(i for i, _ in o_feedback)

    # Terminal state: histories must match slot-for-slot (same appends, same
    # eviction draws), plus totals, draw counters, item counters.
    for u in range(8):
        assert sampler.hist[u, : int(sampler.hist_len[u])].tolist() == \
            oracle.user_history[u]
        assert sampler.total[u] == oracle.user_total[u]
        assert sampler.draws[u] == oracle.user_draws[u]
    for i in range(12):
        assert cut.counts[i] == oracle.item_interactions[i]


def test_sampler_skip_cuts_histories_unbounded():
    sampler = UserReservoirSampler(user_cut=2, seed=1, skip_cuts=True)
    users = np.zeros(50, dtype=np.int64)
    items = np.arange(50, dtype=np.int64)
    pairs, feedback = sampler.fire(users, items, np.ones(50, dtype=bool))
    assert sampler.hist_len[0] == 50
    assert len(feedback) == 0
    # Every ordered pair in both directions exactly once: 50*49 pairs.
    assert len(pairs) == 50 * 49
    agg = aggregate(pairs)
    assert all(v == 1 for v in agg.values())
    assert len(agg) == 50 * 49


def test_reservoir_retention_is_uniform():
    """Algorithm-R property (UserInteractionCounter...java:206-245): after a
    user streams M distinct items through a kMax reservoir, every stream
    position is retained with probability kMax/M — the sketch is an unbiased
    uniform sample, not recency-biased."""
    k_max, m, n_seeds = 8, 64, 400
    hits = np.zeros(m, dtype=np.int64)
    items = np.arange(m, dtype=np.int64)
    users = np.zeros(m, dtype=np.int64)
    sampled = np.ones(m, dtype=bool)
    for seed in range(n_seeds):
        s = UserReservoirSampler(k_max, seed=seed * 7919 + 1, skip_cuts=False)
        s.fire(users, items, sampled)
        assert int(s.hist_len[0]) == k_max  # reservoir exactly full, every seed
        kept = s.hist[0, : int(s.hist_len[0])]
        hits[kept] += 1
    p = k_max / m
    freq = hits / n_seeds
    # Binomial(n_seeds, p) per position: sigma ~ 0.0166 -> +-5 sigma bounds.
    sigma = (p * (1 - p) / n_seeds) ** 0.5
    assert freq.min() > p - 5 * sigma, (freq.min(), p)
    assert freq.max() < p + 5 * sigma, (freq.max(), p)


def test_checkpoint_hist_zeroed_after_empty_growth():
    """hist grows with np.empty (host-floor optimization); the
    persistence view must still be deterministic — every cell beyond a
    row's hist_len reads zero in checkpoint_state."""
    import numpy as np

    from tpu_cooccurrence.sampling.reservoir import UserReservoirSampler

    s = UserReservoirSampler(user_cut=5, seed=3, skip_cuts=False)
    rng = np.random.default_rng(1)
    for _w in range(3):
        users = rng.integers(0, 5_000, 4_000).astype(np.int64)  # > 1024 rows
        items = rng.integers(0, 100, 4_000).astype(np.int64)
        s.fire(users, items, np.ones(4_000, dtype=bool))
    assert s.hist.shape[0] > 1024, "growth never happened — test is inert"
    st = s.checkpoint_state(5_000)
    cols = np.arange(st["hist"].shape[1])[None, :]
    dead = cols >= st["hist_len"][:, None]
    assert (st["hist"][dead] == 0).all(), (
        "uninitialized heap bytes leaked into the checkpoint")
