"""Meta-rule test: every registered cooclint rule proves itself.

Parametrised over the live rule registry: each rule must have at least
one positive fixture (a mini repo it flags) and one negative fixture (a
mini repo it passes) in ``tests/rule_fixtures.py``. A rule added
without fixtures fails here by construction — the registry can never
grow a rule whose detection is untested (silent no-op) or whose
precision is untested (false-positive generator).
"""

import pytest

from tpu_cooccurrence.analysis import Analyzer, RULES

from rule_fixtures import FIXTURES


def _materialize(root, files):
    for rel, src in files.items():
        path = root / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(src)
    return root


@pytest.mark.parametrize("rule", sorted(RULES))
def test_rule_has_fixture_entry(rule):
    entry = FIXTURES.get(rule)
    assert entry is not None, (
        f"rule {rule!r} has no entry in tests/rule_fixtures.py — every "
        f"registered rule needs at least one positive and one negative "
        f"fixture")
    assert entry.get("bad"), f"rule {rule!r} has no positive fixture"
    assert entry.get("good"), f"rule {rule!r} has no negative fixture"


def test_no_orphan_fixture_entries():
    """Fixture entries for rules that no longer exist are stale."""
    assert not set(FIXTURES) - set(RULES)


@pytest.mark.parametrize("rule", sorted(RULES))
def test_rule_flags_its_positive_fixtures(rule, tmp_path):
    for i, files in enumerate(FIXTURES[rule]["bad"]):
        root = _materialize(tmp_path / f"bad{i}", files)
        result = Analyzer(str(root), rules=[RULES[rule]],
                          baseline=[]).run()
        assert result.findings, (
            f"rule {rule!r} missed its positive fixture #{i} — the "
            f"violation it exists to catch went undetected")
        assert all(f.rule == rule for f in result.findings)


@pytest.mark.parametrize("rule", sorted(RULES))
def test_rule_passes_its_negative_fixtures(rule, tmp_path):
    for i, files in enumerate(FIXTURES[rule]["good"]):
        root = _materialize(tmp_path / f"good{i}", files)
        result = Analyzer(str(root), rules=[RULES[rule]],
                          baseline=[]).run()
        assert not result.findings, (
            f"rule {rule!r} false-positived on its negative fixture "
            f"#{i}: {result.findings}")
