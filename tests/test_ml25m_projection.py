"""v5e-8 projection constants (VERDICT r3, Next #7).

The only currently-"met" form of the <60 s ML-25M target is the
projection; its psum constant must come from measurement (the tunnel
probe's synchronized-dispatch RTT in TPU_ROUND2.jsonl) or carry an
explicit assumed-default label, and the projection must report error
bars either way.
"""

import json

import numpy as np
import pytest

from tpu_cooccurrence.bench import ml25m, tpu_round2
from tpu_cooccurrence.bench.ml25m import (PSUM_LATENCY_DEFAULT_S,
                                          measured_psum_latency)


@pytest.fixture(scope="module")
def measured_20k():
    """ONE measured stand-in run shared by every projection test: the
    monkeypatched capture file only changes :func:`ml25m.project_v5e8`'s
    constants (arithmetic), never the measured stream numbers — so the
    expensive measurement half runs once per module, not per test. The
    projection tests consume host/device seconds and the window count
    arithmetically, so the stream length only needs enough windows to
    make the per-window collective term visible."""
    with pytest.MonkeyPatch.context() as mp:
        mp.delenv("MOVIELENS_25M", raising=False)  # stand-in stream
        return ml25m.measure_full(8_000, host_only=False)


def test_psum_default_when_no_capture(tmp_path, monkeypatch):
    monkeypatch.setattr(tpu_round2, "OUT", str(tmp_path / "none.jsonl"))
    lat, src = measured_psum_latency()
    assert lat == PSUM_LATENCY_DEFAULT_S
    assert "assumed" in src


def test_psum_reads_latest_probe_capture(tmp_path, monkeypatch):
    out = tmp_path / "rounds.jsonl"
    lines = [
        {"name": "env", "ok": True},
        {"name": "tunnel-probe", "ok": True, "sync_ms_per_dispatch": 9.0,
         "ts": "2026-01-01 00:00:00"},
        {"name": "tunnel-probe", "ok": False, "error": "dead"},
        # Latest GOOD capture wins:
        {"name": "tunnel-probe", "ok": True, "sync_ms_per_dispatch": 3.5,
         "ts": "2026-02-02 00:00:00"},
        "not json at all",
    ]
    with open(out, "w") as f:
        for obj in lines:
            f.write((obj if isinstance(obj, str) else json.dumps(obj))
                    + "\n")
    monkeypatch.setattr(tpu_round2, "OUT", str(out))
    lat, src = measured_psum_latency()
    assert lat == 3.5e-3
    assert "measured" in src and "2026-02-02" in src


def test_sharded_overhead_absent_before_capture(tmp_path, monkeypatch):
    monkeypatch.setattr(tpu_round2, "OUT", str(tmp_path / "none.jsonl"))
    s, src = ml25m.measured_sharded_overhead()
    assert s is None and "no sharded-pallas-1chip" in src


def test_projection_constants_reject_cpu_tagged_rows(tmp_path,
                                                     monkeypatch):
    """A CPU smoke row (jax_platform=cpu) in the tracked JSONL must not
    become a projection constant — same onchip_row predicate as the
    summary (shared altitude, not per-reader filters)."""
    out = tmp_path / "rounds.jsonl"
    with open(out, "w") as f:
        f.write(json.dumps({"name": "tunnel-probe", "ok": True,
                            "jax_platform": "cpu",
                            "sync_ms_per_dispatch": 99.0}) + "\n")
        f.write(json.dumps({"name": "sharded-pallas-1chip", "ok": True,
                            "jax_platform": "cpu",
                            "sharded_overhead_ms_per_window": 13.6})
                + "\n")
    monkeypatch.setattr(tpu_round2, "OUT", str(out))
    lat, src = ml25m.measured_psum_latency()
    assert lat == ml25m.PSUM_LATENCY_DEFAULT_S and "assumed" in src
    s, src2 = ml25m.measured_sharded_overhead()
    assert s is None


def test_projection_point_uses_measured_overhead(tmp_path, monkeypatch,
                                                 measured_20k):
    """VERDICT r4 Next #7: once a sharded-pallas-1chip capture exists,
    the projection's per-window collective term is the measured
    shard_map+psum overhead — zero assumed constants — and the source
    strings say which measurement each constant came from."""
    out_file = tmp_path / "rounds.jsonl"
    with open(out_file, "w") as f:
        f.write(json.dumps({"name": "tunnel-probe", "ok": True,
                            "sync_ms_per_dispatch": 8.0,
                            "ts": "2026-03-03 00:00:00"}) + "\n")
        f.write(json.dumps({"name": "sharded-pallas-1chip", "ok": True,
                            "sharded_overhead_ms_per_window": 1.25,
                            "ts": "2026-03-04 00:00:00"}) + "\n")
    monkeypatch.setattr(tpu_round2, "OUT", str(out_file))
    out = ml25m.project_v5e8(measured_20k)
    assert out["psum_latency_s"] == 1.25e-3
    assert "measured 1-chip shard_map+psum" in out["psum_latency_source"]
    assert "2026-03-04" in out["psum_latency_source"]
    assert "assumed" not in out["psum_latency_source"]
    assert "assumed" not in out["psum_latency_upper_source"]
    host = out["host_sample_seconds"]
    dev = out["device_score_seconds"]
    w = out["windows"]
    np.testing.assert_allclose(
        out["v5e8_projected_seconds"],
        round(host + dev / 8 + w * 1.25e-3, 2), atol=0.011)
    # Upper bound: max(measured sync RTT, 2x point) per window.
    np.testing.assert_allclose(
        out["v5e8_projected_range"][1],
        round(host + dev / 8 + w * 8.0e-3, 2), atol=0.011)


def test_projection_carries_error_bars(tmp_path, monkeypatch,
                                       measured_20k):
    """run_full's projection reports point, range, and both constants'
    provenance; a measured tunnel RTT bounds the range from above but
    must NOT inflate the point estimate (tunnel transport is not an
    on-pod cost). Tiny stand-in stream keeps this a unit test."""
    out_file = tmp_path / "rounds.jsonl"
    with open(out_file, "w") as f:
        f.write(json.dumps({"name": "tunnel-probe", "ok": True,
                            "sync_ms_per_dispatch": 8.0,
                            "ts": "2026-03-03 00:00:00"}) + "\n")
    monkeypatch.setattr(tpu_round2, "OUT", str(out_file))
    out = ml25m.project_v5e8(measured_20k)
    assert out["synthetic_standin"] is True
    low, high = out["v5e8_projected_range"]
    assert low <= out["v5e8_projected_seconds"] <= high
    # Point estimate uses the on-pod allowance, not the tunnel RTT.
    assert out["psum_latency_s"] == PSUM_LATENCY_DEFAULT_S
    assert "on-pod" in out["psum_latency_source"]
    assert out["psum_latency_upper_s"] == 8.0e-3
    assert "tunnel transport" in out["psum_latency_upper_source"]
    # The range endpoints follow the stated formula.
    host = out["host_sample_seconds"]
    dev = out["device_score_seconds"]
    w = out["windows"]
    np.testing.assert_allclose(low, round(host + dev / 8, 2), atol=0.011)
    np.testing.assert_allclose(
        high, round(host + dev / 8 + w * 8.0e-3, 2), atol=0.011)


def test_partitioned_projection_labeled(tmp_path, monkeypatch,
                                        measured_20k):
    """The secondary host-partitioned projection must be present,
    follow host/8 + device/8 + windows*psum, and carry the
    assumed-linear-scaling label (it is arithmetic, not measurement)."""
    monkeypatch.setattr(tpu_round2, "OUT", str(tmp_path / "none.jsonl"))
    out = ml25m.project_v5e8(measured_20k)
    host = out["host_sample_seconds"]
    dev = out["device_score_seconds"]
    w = out["windows"]
    np.testing.assert_allclose(
        out["v5e8_partitioned_projected_seconds"],
        round(host / 8 + dev / 8 + w * out["psum_latency_s"], 2),
        atol=0.011)
    assert "assumed" in out["v5e8_partitioned_note"]
    assert "--partition-sampling" in out["v5e8_partitioned_note"]


def test_sparse_host_floor_mocked_mode(monkeypatch):
    """--host-only --backend sparse runs the REAL sparse scorer with
    device dispatches stubbed (reproducible sparse host floor), and the
    patches are restored afterwards."""
    import tpu_cooccurrence.state.sparse_scorer as ss

    monkeypatch.delenv("MOVIELENS_25M", raising=False)  # stand-in stream
    orig = ss._apply_update
    out = ml25m.run_full(20_000, host_only=True,
                         backend=ml25m.Backend.SPARSE)
    assert out["backend"] == "sparse-device-mocked"
    assert out["windows"] > 0 and out["pairs"] > 0
    assert ss._apply_update is orig, "device stubs leaked"
