"""Per-rule positive/negative fixture registry for the meta-rule test.

``FIXTURES`` maps every registered cooclint rule name to at least one
``bad`` fixture (a mini repo the rule MUST flag) and one ``good``
fixture (a mini repo the rule MUST pass). Each fixture is a dict of
repo-relative path -> source text; ``tests/test_meta_rules.py``
materialises it under ``tmp_path`` and runs the one rule over it.

The point is structural: a rule with no positive fixture could rot into
a no-op without any test noticing, and a rule with no negative fixture
could grow false positives the repo-clean gate only reports once they
hit real code. The meta test fails the moment a new rule registers
without an entry here.

This file's raw text necessarily quotes bad fault-site spec strings
(the same reason tests/test_cooclint.py opts out), so:
# cooclint: disable-file=fault-site
"""

from typing import Dict, List

from tpu_cooccurrence.robustness.gang import GANG_SITES

_FIRE_ALL_GANG_SITES = "def drive(plan):\n" + "".join(
    f'    plan.fire("{site}")\n' for site in sorted(GANG_SITES))

#: rule name -> {"bad": [files-dict, ...], "good": [files-dict, ...]}
FIXTURES: Dict[str, Dict[str, List[Dict[str, str]]]] = {
    "ckpt-format-roundtrip": {
        "bad": [{
            "tpu_cooccurrence/state/checkpoint.py": (
                "def save():\n"
                "    meta = {\"windows\": 1, \"orphan\": 2}\n\n\n"
                "def restore(meta):\n"
                "    return meta[\"windows\"]\n"),
            "tpu_cooccurrence/state/delta.py": (
                "def encode():\n"
                "    header = {\"gen\": 1}\n\n\n"
                "def decode(header):\n"
                "    return header[\"gen\"]\n"),
            "tests/test_fmt_fixture.py":
                "KEYS = {\"windows\", \"orphan\", \"gen\"}\n",
        }],
        "good": [{
            "tpu_cooccurrence/state/checkpoint.py": (
                "def save():\n"
                "    meta = {\"windows\": 1}\n\n\n"
                "def restore(meta):\n"
                "    return meta[\"windows\"]\n"),
            "tpu_cooccurrence/state/delta.py": (
                "def encode():\n"
                "    header = {\"gen\": 1}\n\n\n"
                "def decode(header):\n"
                "    return header[\"gen\"]\n"),
            "tests/test_fmt_fixture.py":
                "KEYS = {\"windows\", \"gen\"}\n",
        }],
    },
    "cli-flag": {
        "bad": [{
            "tpu_cooccurrence/config.py": (
                "import argparse\n"
                "import dataclasses\n\n\n"
                "@dataclasses.dataclass\n"
                "class Config:\n"
                "    top_k: int = 10\n\n\n"
                "def from_args():\n"
                "    p = argparse.ArgumentParser()\n"
                '    p.add_argument("--top-k", type=int, dest="top_k")\n'
                '    p.add_argument("--mystery-flag", type=int,'
                ' dest="mystery")\n'
                "    return p\n"),
            "README.md": "Flags: `--top-k`.\n",
        }],
        "good": [{
            "tpu_cooccurrence/config.py": (
                "import argparse\n"
                "import dataclasses\n\n\n"
                "@dataclasses.dataclass\n"
                "class Config:\n"
                "    top_k: int = 10\n\n\n"
                "def from_args():\n"
                "    p = argparse.ArgumentParser()\n"
                '    p.add_argument("--top-k", type=int, dest="top_k")\n'
                "    return p\n"),
            "README.md": "Flags: `--top-k`.\n",
        }],
    },
    "collective-watchdog": {
        "bad": [{
            "tpu_cooccurrence/sampling/multihost.py": (
                "from jax.experimental import multihost_utils\n\n\n"
                "def exchange(vec):\n"
                "    return multihost_utils.process_allgather(vec)\n"),
        }],
        "good": [{
            "tpu_cooccurrence/sampling/multihost.py": (
                "from tpu_cooccurrence.parallel.distributed import (\n"
                "    gang_barrier, guarded_allgather)\n\n\n"
                "def exchange(vec):\n"
                '    gang_barrier("x")\n'
                "    return guarded_allgather(vec)\n"),
        }],
    },
    "degrade-registry": {
        "bad": [{
            "tpu_cooccurrence/robustness/degrade.py": (
                "import enum\n\n\n"
                "class DegradationLevel(enum.IntEnum):\n"
                "    NORMAL = 0\n"
                "    SHED_SAMPLING = 1\n\n\n"
                "TRANSITION_RULES = {\n"
                '    "NORMAL": "healthy",\n'
                "}\n"
                "LEVEL_EVENTS = {\n"
                '    "NORMAL": "degrade/enter_normal",\n'
                '    "SHED_SAMPLING": "degrade/enter_shed_sampling",\n'
                "}\n"),
        }],
        "good": [{
            "tpu_cooccurrence/robustness/degrade.py": (
                "import enum\n\n\n"
                "class DegradationLevel(enum.IntEnum):\n"
                "    NORMAL = 0\n"
                "    SHED_SAMPLING = 1\n\n\n"
                "TRANSITION_RULES = {\n"
                '    "NORMAL": "healthy",\n'
                '    "SHED_SAMPLING": "overloaded",\n'
                "}\n"
                "LEVEL_EVENTS = {\n"
                '    "NORMAL": "degrade/enter_normal",\n'
                '    "SHED_SAMPLING": "degrade/enter_shed_sampling",\n'
                "}\n"),
        }],
    },
    "donation-reuse": {
        "bad": [{
            "tpu_cooccurrence/scorer.py": (
                "import functools\n"
                "import jax\n"
                "from .ops.donation import donate_argnums\n\n\n"
                "@functools.partial(jax.jit,"
                " donate_argnums=donate_argnums(0))\n"
                "def update(c, d):\n"
                "    return c + d\n\n\n"
                "class Scorer:\n"
                "    def step(self, d):\n"
                "        out = update(self.cnt, d)\n"
                "        return self.cnt.sum()\n"),
        }],
        "good": [{
            "tpu_cooccurrence/scorer.py": (
                "import functools\n"
                "import jax\n"
                "from .ops.donation import donate_argnums\n\n\n"
                "@functools.partial(jax.jit,"
                " donate_argnums=donate_argnums(0))\n"
                "def update(c, d):\n"
                "    return c + d\n\n\n"
                "class Scorer:\n"
                "    def step(self, d):\n"
                "        self.cnt = update(self.cnt, d)\n"
                "        return self.cnt.sum()\n"),
        }],
    },
    "fault-site": {
        "bad": [{
            "tpu_cooccurrence/chaos_caller.py": (
                "def f(plan):\n"
                '    plan.fire("not_a_site", seq=1)\n'),
        }],
        "good": [{
            "tpu_cooccurrence/chaos_caller.py": (
                "def f(plan):\n"
                '    plan.fire("window_fire", seq=1)\n'),
        }],
    },
    "fold-dtype-guard": {
        "bad": [{
            "tpu_cooccurrence/ops/aggregate.py": (
                "import numpy as np\n"
                "def aggregate_window_coo(src, dst, delta,"
                " return_key=False):\n"
                "    return src, dst, delta\n"),
        }],
        "good": [{
            "tpu_cooccurrence/ops/aggregate.py": (
                "import numpy as np\n"
                "def aggregate_window_coo(src, dst, delta,"
                " return_key=False):\n"
                "    if not np.issubdtype(delta.dtype, np.integer):\n"
                '        raise TypeError("delta dtype")\n'
                "    return src, dst, delta\n"),
        }],
    },
    "fused-fallback-registry": {
        "bad": [{
            "tpu_cooccurrence/parallel/sharded_sparse.py": (
                "class S:\n"
                "    def _fallback_chained(self, reason):\n"
                "        self.last_fallback_reason = reason\n\n"
                "    def window(self, cold):\n"
                "        if cold:\n"
                "            self._fallback_chained('plan-rebuild')\n"),
            "docs/ARCHITECTURE.md": "no fallback table here\n",
            "tests/test_fb_fixture.py":
                "def test_nothing():\n    pass\n",
        }],
        "good": [{
            "tpu_cooccurrence/parallel/sharded_sparse.py": (
                "class S:\n"
                "    def _fallback_chained(self, reason):\n"
                "        self.last_fallback_reason = reason\n\n"
                "    def window(self, cold):\n"
                "        if cold:\n"
                "            self._fallback_chained('plan-rebuild')\n"),
            "docs/ARCHITECTURE.md": "| `plan-rebuild` | cold plans |\n",
            "tests/test_fb_fixture.py": (
                "def test_cold():\n"
                "    assert reason == 'plan-rebuild'\n"),
        }],
    },
    "gang-fault-sites": {
        "bad": [{
            # faults.py present but nothing fires any gang site: every
            # GANG_SITES member is an unplugged chaos site.
            "tpu_cooccurrence/robustness/faults.py": "SITES = {}\n",
        }],
        "good": [{
            "tpu_cooccurrence/robustness/faults.py": "SITES = {}\n",
            "tpu_cooccurrence/robustness/gang_driver.py":
                _FIRE_ALL_GANG_SITES,
        }],
    },
    "ingest-offset-registry": {
        "bad": [{
            "tpu_cooccurrence/io/source.py": (
                "def offsets_state(self):\n"
                "    offsets = {\"v\": 1, \"orphan\": 2}\n"
                "    return offsets\n\n\n"
                "def restore_offsets(self, state):\n"
                "    self.v = state.get(\"v\")\n"),
            "tpu_cooccurrence/io/partitioned.py": (
                "def offsets_state(self):\n"
                "    partitions = {}\n"
                "    partitions[name] = {\"byte_offset\": 0}\n"
                "    offsets = {\"v\": 1, \"partitions\": partitions}\n"
                "    return offsets\n\n\n"
                "def restore_offsets(self, state):\n"
                "    self.v = state.get(\"v\")\n"
                "    for e in state[\"partitions\"].values():\n"
                "        self.b = e[\"byte_offset\"]\n"),
            "tests/test_ingest_fixture.py": (
                "KEYS = {\"v\", \"orphan\", \"partitions\","
                " \"byte_offset\"}\n"),
        }],
        "good": [{
            "tpu_cooccurrence/io/source.py": (
                "def offsets_state(self):\n"
                "    in_flight = {\"path\": self.p}\n"
                "    offsets = {\"v\": 1, \"in_flight\": in_flight}\n"
                "    return offsets\n\n\n"
                "def restore_offsets(self, state):\n"
                "    self.v = state.get(\"v\")\n"
                "    guard = state.get(\"in_flight\")\n"
                "    self.p = guard[\"path\"]\n"),
            "tpu_cooccurrence/io/partitioned.py": (
                "def offsets_state(self):\n"
                "    partitions = {}\n"
                "    partitions[name] = {\"byte_offset\": 0}\n"
                "    offsets = {\"v\": 1, \"partitions\": partitions}\n"
                "    return offsets\n\n\n"
                "def restore_offsets(self, state):\n"
                "    self.v = state.get(\"v\")\n"
                "    for e in state[\"partitions\"].values():\n"
                "        self.b = e[\"byte_offset\"]\n"),
            "tests/test_ingest_fixture.py": (
                "KEYS = {\"v\", \"in_flight\", \"path\","
                " \"partitions\", \"byte_offset\"}\n"),
        }],
    },
    "jit-purity": {
        "bad": [{
            # Host RNG two hops below the jit entry: only visible to
            # the whole-program call-graph pass.
            "tpu_cooccurrence/job.py": (
                "import jax\n"
                "import numpy as np\n\n\n"
                "def noise(shape):\n"
                "    return np.random.standard_normal(shape)\n\n\n"
                "def helper(x):\n"
                "    return x + noise(x.shape)\n\n\n"
                "@jax.jit\n"
                "def entry(x):\n"
                "    return helper(x)\n"),
        }],
        "good": [{
            "tpu_cooccurrence/job.py": (
                "import functools\n"
                "import jax\n"
                "import numpy as np\n\n\n"
                "@functools.partial(jax.jit,"
                " static_argnames=(\"k\",))\n"
                "def topk(vals, k):\n"
                "    return int(k) + vals.sum()\n\n\n"
                "def host_helper(x):\n"
                "    return float(np.asarray(x).sum())\n"),
        }],
    },
    "journal-schema-registry": {
        "bad": [{
            "tpu_cooccurrence/writer.py": (
                "class J:\n"
                "    def emit(self):\n"
                "        self.journal.record({'v': 1, 'seq': 1,\n"
                "                             'warp_factor': 9})\n"),
        }],
        "good": [{
            "tpu_cooccurrence/writer.py": (
                "class J:\n"
                "    def emit(self):\n"
                "        self.journal.record({'v': 1, 'seq': 1})\n"),
        }],
    },
    "lock-annotation": {
        "bad": [{
            "tpu_cooccurrence/pipeline.py":
                "import threading\nLOCK = threading.Lock()\n",
        }],
        "good": [{
            "tpu_cooccurrence/pipeline.py": (
                "import threading\n"
                "# lock-ordering: leaf lock, never held across "
                "registry locks\n"
                "LOCK = threading.Lock()\n"),
        }],
    },
    "lock-discipline": {
        "bad": [{
            "tpu_cooccurrence/pipeline.py": (
                "class PipelineWorker:\n"
                "    def record_upload(self, ledger, arrays):\n"
                "        n = sum(int(a.nbytes) for a in arrays)\n"
                "        ledger.h2d_bytes += n\n"
                "        ledger.h2d_calls += 1\n"),
        }],
        "good": [{
            "tpu_cooccurrence/pipeline.py": (
                "class PipelineWorker:\n"
                "    def record_upload(self, ledger, n):\n"
                "        with ledger._lock:\n"
                "            ledger.h2d_bytes += n\n"
                "            ledger.h2d_calls += 1\n"),
        }],
    },
    "metric-name": {
        "bad": [{
            "tpu_cooccurrence/worker.py": (
                "from .registry import REGISTRY\n"
                'g = REGISTRY.gauge("cooc_bogus_thing", help="x")\n'),
        }],
        "good": [{
            "tpu_cooccurrence/worker.py": (
                "from .registry import REGISTRY\n"
                'g = REGISTRY.gauge("cooc_windows_fired", help="x")\n'),
        }],
    },
    "narrow-cast-guard": {
        "bad": [{
            "tpu_cooccurrence/state/packing.py": (
                "import numpy as np\n\n\n"
                "def shrink(deltas):\n"
                "    return deltas.astype(np.int16)\n"),
        }],
        "good": [{
            # Guard evidence in the enclosing function (iinfo bound).
            "tpu_cooccurrence/state/packing.py": (
                "import numpy as np\n\n\n"
                "def shrink(deltas):\n"
                "    lim = np.iinfo(np.int16).max\n"
                "    if deltas.max() > lim:\n"
                "        raise OverflowError\n"
                "    return deltas.astype(np.int16)\n"),
        }, {
            # The immediate sign-extend idiom never stores narrow.
            "tpu_cooccurrence/state/packing.py": (
                "import numpy as np\n\n\n"
                "def widen(vals):\n"
                "    return vals.astype(np.int16).astype(np.int32)\n"),
        }],
    },
    "native-dtype": {
        "bad": [{
            "tpu_cooccurrence/native/__init__.py": (
                "import numpy as np\n"
                "def call(x):\n"
                "    lib.kernel(_ptr64(x), 3)\n"),
        }],
        "good": [{
            "tpu_cooccurrence/native/__init__.py": (
                "import numpy as np\n"
                "def call(x):\n"
                "    x = np.ascontiguousarray(x, dtype=np.int64)\n"
                "    lib.kernel(_ptr64(x), 3)\n"),
        }],
    },
    "pallas-kernel-registry": {
        "bad": [{
            "tpu_cooccurrence/ops/pallas_score.py": (
                "from jax.experimental import pallas as pl\n\n\n"
                "def _my_kernel_core(x):\n"
                "    return pl.pallas_call(None)(x)\n\n\n"
                "def my_kernel_wrapper(x):\n"
                "    return _my_kernel_core(x)\n"),
            "tests/test_parity_fixture.py":
                "def test_nothing():\n    pass\n",
            "docs/ARCHITECTURE.md":
                "| `_my_kernel_core` | streaming thing |\n",
        }],
        "good": [{
            "tpu_cooccurrence/ops/pallas_score.py": (
                "from jax.experimental import pallas as pl\n\n\n"
                "def _my_kernel_core(x):\n"
                "    return pl.pallas_call(None)(x)\n\n\n"
                "def my_kernel_wrapper(x):\n"
                "    return _my_kernel_core(x)\n"),
            "tests/test_parity_fixture.py":
                "def test_parity():\n    assert my_kernel_wrapper\n",
            "docs/ARCHITECTURE.md":
                "| `_my_kernel_core` | streaming thing |\n",
        }],
    },
    "replica-generation-tag": {
        "bad": [{
            "tpu_cooccurrence/serving/replica.py": (
                "from ..observability.http import MetricsServer\n\n\n"
                "class ReplicaServer(MetricsServer):\n"
                "    def recommend(self, query):\n"
                '        return 200, {"items": []}\n'),
        }],
        "good": [{
            "tpu_cooccurrence/serving/replica.py": (
                "from ..observability.http import MetricsServer\n\n\n"
                "class ReplicaServer(MetricsServer):\n"
                "    def recommend(self, query):\n"
                '        return 200, {"items": [], "generation": 1}\n'),
        }],
    },
    "scale-policy-registry": {
        "bad": [{
            "tpu_cooccurrence/robustness/autoscale.py": (
                "class ScalePolicy:\n"
                "    def decide(self, *a):\n"
                "        raise NotImplementedError\n\n\n"
                "class MyLadderPolicy(ScalePolicy):\n"
                "    pass\n\n\n"
                "class MySteppedPolicy(MyLadderPolicy):\n"
                "    pass\n"),
            "tests/test_policy_fixture.py": (
                "def test_hysteresis():\n"
                "    assert MyLadderPolicy\n"),
            "docs/ARCHITECTURE.md": (
                "| `MyLadderPolicy` | ladder |\n"
                "| `MySteppedPolicy` | stepped |\n"),
        }],
        "good": [{
            "tpu_cooccurrence/robustness/autoscale.py": (
                "class ScalePolicy:\n"
                "    def decide(self, *a):\n"
                "        raise NotImplementedError\n\n\n"
                "class MyLadderPolicy(ScalePolicy):\n"
                "    pass\n\n\n"
                "class MySteppedPolicy(MyLadderPolicy):\n"
                "    pass\n"),
            "tests/test_policy_fixture.py": (
                "def test_hysteresis():\n"
                "    assert MyLadderPolicy and MySteppedPolicy\n"),
            "docs/ARCHITECTURE.md": (
                "| `MyLadderPolicy` | ladder |\n"
                "| `MySteppedPolicy` | stepped |\n"),
        }],
    },
    "serving-route": {
        "bad": [{
            "tpu_cooccurrence/observability/http.py": (
                "ROUTE_METRICS = {\n"
                '    "/metrics": "cooc_scrape_seconds",\n'
                "}\n\n\n"
                "def do_GET(path):\n"
                '    if path == "/secret":\n'
                '        return "ok"\n'),
            "README.md": "curl /metrics\n",
            "tests/test_routes_fixture.py":
                'R = ["/metrics"]\n',
        }],
        "good": [{
            "tpu_cooccurrence/observability/http.py": (
                "ROUTE_METRICS = {\n"
                '    "/metrics": "cooc_scrape_seconds",\n'
                '    "/healthz": "cooc_healthz_seconds",\n'
                "}\n"),
            "README.md": "curl /metrics /healthz\n",
            "tests/test_routes_fixture.py":
                'R = ["/metrics", "/healthz"]\n',
        }],
    },
    "state-store-registry": {
        "bad": [{
            "tpu_cooccurrence/state/store.py": (
                "class StateStore:\n"
                "    def checkpoint_state(self):\n"
                "        raise NotImplementedError\n\n\n"
                "class MyDirectStore(StateStore):\n"
                "    pass\n\n\n"
                "class MyTieredStore(MyDirectStore):\n"
                "    pass\n"),
            "tests/test_store_fixture.py": (
                "def test_round_trip():\n"
                "    assert MyDirectStore\n"),
            "docs/ARCHITECTURE.md": (
                "| `MyDirectStore` | direct |\n"
                "| `MyTieredStore` | tiered |\n"),
        }],
        "good": [{
            "tpu_cooccurrence/state/store.py": (
                "class StateStore:\n"
                "    def checkpoint_state(self):\n"
                "        raise NotImplementedError\n\n\n"
                "class MyDirectStore(StateStore):\n"
                "    pass\n\n\n"
                "class MyTieredStore(MyDirectStore):\n"
                "    pass\n"),
            "tests/test_store_fixture.py": (
                "def test_round_trip():\n"
                "    assert MyDirectStore and MyTieredStore\n"),
            "docs/ARCHITECTURE.md": (
                "| `MyDirectStore` | direct |\n"
                "| `MyTieredStore` | tiered |\n"),
        }],
    },
    "thread-ownership": {
        "bad": [{
            # The pre-fix PR-2 shape: spawned worker and main thread
            # both write the ledger's byte totals, no lock anywhere.
            "tpu_cooccurrence/job.py": (
                "import threading\n\n\n"
                "class TransferLedger:\n"
                "    def __init__(self):\n"
                "        self.h2d_bytes = 0\n\n"
                "    def add(self, n):\n"
                "        self.h2d_bytes += n\n\n\n"
                "def scorer_worker(ledger):\n"
                "    ledger.h2d_bytes += 4\n\n\n"
                "def main():\n"
                "    ledger = TransferLedger()\n"
                "    threading.Thread(target=scorer_worker,\n"
                '                     name="scorer").start()\n'
                "    ledger.add(3)\n"),
        }],
        "good": [{
            "tpu_cooccurrence/job.py": (
                "import threading\n\n\n"
                "class TransferLedger:\n"
                "    def __init__(self):\n"
                "        self.h2d_bytes = 0\n\n"
                "    def add(self, n):\n"
                "        with self._lock:\n"
                "            self.h2d_bytes += n\n\n\n"
                "def scorer_worker(ledger):\n"
                "    with ledger._lock:\n"
                "        ledger.h2d_bytes += 4\n\n\n"
                "def main():\n"
                "    ledger = TransferLedger()\n"
                "    threading.Thread(target=scorer_worker,\n"
                '                     name="scorer").start()\n'
                "    ledger.add(3)\n"),
        }],
    },
    "tuning-magic-number": {
        "bad": [{
            "tpu_cooccurrence/ops/plan.py": (
                "def plan(rows):\n"
                "    if rows < 256:\n"
                "        return None\n"
                "    return rows\n"),
        }],
        "good": [{
            # Same literal outside the hot-path prefixes is style, not
            # a smuggled tuning default.
            "tpu_cooccurrence/config.py": (
                "def plan(rows):\n"
                "    if rows < 256:\n"
                "        return None\n"
                "    return rows\n"),
        }],
    },
    "tuning-registry": {
        "bad": [{
            "tpu_cooccurrence/worker.py": (
                "import os\n"
                'budget = os.environ.get("TPU_COOC_NOT_A_KNOB", "0")\n'),
        }],
        "good": [{
            "tpu_cooccurrence/worker.py": (
                "from tpu_cooccurrence import tuning\n"
                'rid = tuning.env_read("TPU_COOC_RUN_ID")\n'),
        }],
    },
    "wire-codec-roundtrip": {
        "bad": [{
            "tpu_cooccurrence/state/wire.py": (
                "def encode_slab(x):\n"
                "    return bytes(x)\n"),
            "tests/test_wire_fixture.py":
                "def test_rt():\n    assert encode_slab\n",
        }],
        "good": [{
            "tpu_cooccurrence/state/wire.py": (
                "def encode_slab(x):\n"
                "    return bytes(x)\n\n\n"
                "def decode_slab(b):\n"
                "    return list(b)\n"),
            "tests/test_wire_fixture.py": (
                "def test_rt():\n"
                "    assert encode_slab and decode_slab\n"),
        }],
    },
}
