"""IO layer tests: file monitor ordering/process-once, parsing, generators."""

import os
import time

import numpy as np

from tpu_cooccurrence.io.parse import batched_lines, parse_lines
from tpu_cooccurrence.io.source import FileMonitorSource
from tpu_cooccurrence.io.synthetic import (
    word_cooccurrence_stream,
    write_interactions_csv,
    zipfian_interactions,
)
from tpu_cooccurrence.metrics import Counters, SPLIT_READER_NUM_SPLITS


def test_parse_lines():
    u, i, t = parse_lines(["1,2,3", "4,5,6"])
    np.testing.assert_array_equal(u, [1, 4])
    np.testing.assert_array_equal(i, [2, 5])
    np.testing.assert_array_equal(t, [3, 6])


def test_batched_lines():
    batches = list(batched_lines((f"{n},{n},{n}" for n in range(10)), batch_size=4))
    assert [len(b[0]) for b in batches] == [4, 4, 2]


def test_batched_lines_latency_flush():
    """--buffer-timeout semantics: an aged partial batch flushes on the
    continuous source's idle heartbeat instead of waiting for batch_size."""
    import time as _time

    def stream():
        yield "1,1,1"
        yield "2,2,2"
        _time.sleep(0.03)
        yield None  # idle heartbeat: batch is now older than the bound
        yield "3,3,3"
        yield None  # fresh batch, not aged: no flush
        _time.sleep(0.03)
        yield None  # aged now: flush

    batches = list(batched_lines(stream(), batch_size=100,
                                 max_latency_s=0.02))
    assert [b[0].tolist() for b in batches] == [[1, 2], [3]]


def test_batched_lines_heartbeats_ignored_without_latency_bound():
    batches = list(batched_lines(
        iter(["1,1,1", None, "2,2,2", None]), batch_size=100))
    assert [b[0].tolist() for b in batches] == [[1, 2]]


def test_source_modification_time_order(tmp_path):
    # Reference forwards splits sorted by modification time
    # (ContinuousFileMonitoringFunction.java:239-257).
    a = tmp_path / "a.csv"
    b = tmp_path / "b.csv"
    a.write_text("1,1,1\n")
    b.write_text("2,2,2\n")
    now = time.time()
    os.utime(b, (now - 100, now - 100))  # b is older -> must come first
    os.utime(a, (now, now))
    counters = Counters()
    src = FileMonitorSource(str(tmp_path), counters)
    assert list(src.lines()) == ["2,2,2", "1,1,1"]
    assert counters.get(SPLIT_READER_NUM_SPLITS) == 2


def test_source_process_once_skips_consumed(tmp_path):
    f = tmp_path / "a.csv"
    f.write_text("1,1,1\n")
    src = FileMonitorSource(str(f))
    assert len(list(src.lines())) == 1
    # Same mtime on second scan: nothing new.
    assert list(src.lines()) == []


def test_source_hidden_files_skipped(tmp_path):
    (tmp_path / ".hidden").write_text("9,9,9\n")
    (tmp_path / "_partial").write_text("8,8,8\n")
    (tmp_path / "ok.csv").write_text("1,1,1\n")
    src = FileMonitorSource(str(tmp_path))
    assert list(src.lines()) == ["1,1,1"]


def test_source_checkpoint_roundtrip(tmp_path):
    f = tmp_path / "a.csv"
    f.write_text("1,1,1\n")
    src = FileMonitorSource(str(f))
    list(src.lines())
    state = src.checkpoint_state()
    src2 = FileMonitorSource(str(f))
    src2.restore_state(state)
    assert list(src2.lines()) == []


def test_zipfian_shapes_and_skew():
    users, items, ts = zipfian_interactions(
        10_000, n_items=1000, n_users=50, alpha=1.1, seed=1)
    assert len(users) == len(items) == len(ts) == 10_000
    assert (np.diff(ts) >= 0).all()
    # Zipf: rank-0 item must dominate.
    counts = np.bincount(items, minlength=1000)
    assert counts[0] > counts[100:].max()


def test_word_cooccurrence_stream():
    users, items, ts = word_cooccurrence_stream("a b a\nc b\n")
    # line 0: a b a -> user 0 three items; line 1: c b.
    np.testing.assert_array_equal(users, [0, 0, 0, 1, 1])
    np.testing.assert_array_equal(items, [0, 1, 0, 2, 1])


def test_write_interactions_csv_roundtrip(tmp_path):
    p = str(tmp_path / "x.csv")
    write_interactions_csv(p, np.array([1, 2]), np.array([3, 4]),
                           np.array([5, 6]))
    u, i, t = parse_lines(open(p).read().splitlines())
    np.testing.assert_array_equal(u, [1, 2])
    np.testing.assert_array_equal(i, [3, 4])


def test_midfile_resume_with_shared_mtime(tmp_path):
    """Files sharing mtime_ns: a checkpoint mid-way through the second must
    resume there — not re-read the first, not lose the second's tail."""
    import os

    from tpu_cooccurrence.io.source import FileMonitorSource

    a, b = tmp_path / "a.csv", tmp_path / "b.csv"
    a.write_text("a1\na2\n")
    b.write_text("b1\nb2\nb3\n")
    t = os.stat(a).st_mtime_ns
    os.utime(b, ns=(t, t))  # identical mtime

    src = FileMonitorSource(str(tmp_path))
    it = src.lines()
    got = [next(it) for _ in range(3)]   # a1 a2 b1
    assert got == ["a1", "a2", "b1"]
    state = src.checkpoint_state()

    src2 = FileMonitorSource(str(tmp_path))
    src2.restore_state(state)
    assert list(src2.lines()) == ["b2", "b3"]


def test_parse_lines_fast_path_rejects_divergent_inputs():
    """The numpy fast parse must not silently accept what the reference's
    per-line Integer.parseInt would reject (floats, comments, blanks,
    overflow) — each falls back and raises, or parses identically."""
    import pytest

    from tpu_cooccurrence.io.parse import parse_lines

    ok_u, ok_i, ok_t = parse_lines(["1,2,3", "-4,5,6"])
    np.testing.assert_array_equal(ok_u, [1, -4])
    for bad in (["1.9,2,3"], ["1e3,2,3"], ["#1,2,3"], ["1,2,3", ""],
                ["1,2"], ["1,2,3,4"]):
        with pytest.raises(ValueError):
            parse_lines(bad)
    with pytest.raises((ValueError, OverflowError)):
        parse_lines(["99999999999999999999,1,2"])


def test_parse_error_carries_provenance_and_raw_line():
    """Satellite fix (ISSUE 5): every parse rejection names path:lineno
    and the offending raw line — independent of quarantine being on."""
    import pytest

    from tpu_cooccurrence.io.parse import ParseError, parse_lines

    with pytest.raises(ParseError) as ei:
        parse_lines(["1,2,3", "not-a-record", "4,5,6"],
                    provenance=[("data.csv", 10), ("data.csv", 11),
                                ("data.csv", 12)])
    err = ei.value
    assert err.source_path == "data.csv" and err.lineno == 11
    assert err.raw == "not-a-record"
    assert "data.csv:11" in str(err) and "not-a-record" in str(err)
    # Without provenance: batch-relative position against "<stream>".
    with pytest.raises(ParseError) as ei:
        parse_lines(["1,2,3", "9,9"])
    assert ei.value.source_path == "<stream>" and ei.value.lineno == 2
    # Out-of-int64-range ids are a provenance-carrying rejection too,
    # not an opaque array-conversion overflow.
    with pytest.raises(ParseError, match="out of int64 range"):
        parse_lines(["99999999999999999999,1,2"])


def test_batched_lines_captures_origin_per_line(tmp_path):
    """The batcher records (path, lineno) per buffered line from the
    source's origin hook, so a mid-batch poison line is named exactly
    (blank lines are counted in file linenos but never buffered)."""
    import pytest

    from tpu_cooccurrence.io.parse import ParseError, batched_lines
    from tpu_cooccurrence.io.source import FileMonitorSource

    p = tmp_path / "in.csv"
    p.write_text("1,2,3\n\n4,5,6\nBAD\n7,8,9\n")
    src = FileMonitorSource(str(p))
    with pytest.raises(ParseError) as ei:
        list(batched_lines(src.lines(), origin=src.origin))
    assert ei.value.source_path == str(p)
    assert ei.value.lineno == 4  # raw file lineno, blank line included
    assert ei.value.raw == "BAD"
