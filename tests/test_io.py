"""IO layer tests: file monitor ordering/process-once, parsing, generators."""

import os
import time

import numpy as np

from tpu_cooccurrence.io.parse import batched_lines, parse_lines
from tpu_cooccurrence.io.source import FileMonitorSource
from tpu_cooccurrence.io.synthetic import (
    word_cooccurrence_stream,
    write_interactions_csv,
    zipfian_interactions,
)
from tpu_cooccurrence.metrics import Counters, SPLIT_READER_NUM_SPLITS


def test_parse_lines():
    u, i, t = parse_lines(["1,2,3", "4,5,6"])
    np.testing.assert_array_equal(u, [1, 4])
    np.testing.assert_array_equal(i, [2, 5])
    np.testing.assert_array_equal(t, [3, 6])


def test_batched_lines():
    batches = list(batched_lines((f"{n},{n},{n}" for n in range(10)), batch_size=4))
    assert [len(b[0]) for b in batches] == [4, 4, 2]


def test_batched_lines_latency_flush():
    """--buffer-timeout semantics: an aged partial batch flushes on the
    continuous source's idle heartbeat instead of waiting for batch_size."""
    import time as _time

    def stream():
        yield "1,1,1"
        yield "2,2,2"
        _time.sleep(0.03)
        yield None  # idle heartbeat: batch is now older than the bound
        yield "3,3,3"
        yield None  # fresh batch, not aged: no flush
        _time.sleep(0.03)
        yield None  # aged now: flush

    batches = list(batched_lines(stream(), batch_size=100,
                                 max_latency_s=0.02))
    assert [b[0].tolist() for b in batches] == [[1, 2], [3]]


def test_batched_lines_heartbeats_ignored_without_latency_bound():
    batches = list(batched_lines(
        iter(["1,1,1", None, "2,2,2", None]), batch_size=100))
    assert [b[0].tolist() for b in batches] == [[1, 2]]


def test_source_modification_time_order(tmp_path):
    # Reference forwards splits sorted by modification time
    # (ContinuousFileMonitoringFunction.java:239-257).
    a = tmp_path / "a.csv"
    b = tmp_path / "b.csv"
    a.write_text("1,1,1\n")
    b.write_text("2,2,2\n")
    now = time.time()
    os.utime(b, (now - 100, now - 100))  # b is older -> must come first
    os.utime(a, (now, now))
    counters = Counters()
    src = FileMonitorSource(str(tmp_path), counters)
    assert list(src.lines()) == ["2,2,2", "1,1,1"]
    assert counters.get(SPLIT_READER_NUM_SPLITS) == 2


def test_source_process_once_skips_consumed(tmp_path):
    f = tmp_path / "a.csv"
    f.write_text("1,1,1\n")
    src = FileMonitorSource(str(f))
    assert len(list(src.lines())) == 1
    # Same mtime on second scan: nothing new.
    assert list(src.lines()) == []


def test_source_hidden_files_skipped(tmp_path):
    (tmp_path / ".hidden").write_text("9,9,9\n")
    (tmp_path / "_partial").write_text("8,8,8\n")
    (tmp_path / "ok.csv").write_text("1,1,1\n")
    src = FileMonitorSource(str(tmp_path))
    assert list(src.lines()) == ["1,1,1"]


def test_source_checkpoint_roundtrip(tmp_path):
    f = tmp_path / "a.csv"
    f.write_text("1,1,1\n")
    src = FileMonitorSource(str(f))
    list(src.lines())
    state = src.checkpoint_state()
    src2 = FileMonitorSource(str(f))
    src2.restore_state(state)
    assert list(src2.lines()) == []


def test_zipfian_shapes_and_skew():
    users, items, ts = zipfian_interactions(
        10_000, n_items=1000, n_users=50, alpha=1.1, seed=1)
    assert len(users) == len(items) == len(ts) == 10_000
    assert (np.diff(ts) >= 0).all()
    # Zipf: rank-0 item must dominate.
    counts = np.bincount(items, minlength=1000)
    assert counts[0] > counts[100:].max()


def test_word_cooccurrence_stream():
    users, items, ts = word_cooccurrence_stream("a b a\nc b\n")
    # line 0: a b a -> user 0 three items; line 1: c b.
    np.testing.assert_array_equal(users, [0, 0, 0, 1, 1])
    np.testing.assert_array_equal(items, [0, 1, 0, 2, 1])


def test_write_interactions_csv_roundtrip(tmp_path):
    p = str(tmp_path / "x.csv")
    write_interactions_csv(p, np.array([1, 2]), np.array([3, 4]),
                           np.array([5, 6]))
    u, i, t = parse_lines(open(p).read().splitlines())
    np.testing.assert_array_equal(u, [1, 2])
    np.testing.assert_array_equal(i, [3, 4])


def test_midfile_resume_with_shared_mtime(tmp_path):
    """Files sharing mtime_ns: a checkpoint mid-way through the second must
    resume there — not re-read the first, not lose the second's tail."""
    import os

    from tpu_cooccurrence.io.source import FileMonitorSource

    a, b = tmp_path / "a.csv", tmp_path / "b.csv"
    a.write_text("a1\na2\n")
    b.write_text("b1\nb2\nb3\n")
    t = os.stat(a).st_mtime_ns
    os.utime(b, ns=(t, t))  # identical mtime

    src = FileMonitorSource(str(tmp_path))
    it = src.lines()
    got = [next(it) for _ in range(3)]   # a1 a2 b1
    assert got == ["a1", "a2", "b1"]
    state = src.checkpoint_state()

    src2 = FileMonitorSource(str(tmp_path))
    src2.restore_state(state)
    assert list(src2.lines()) == ["b2", "b3"]


def test_parse_lines_fast_path_rejects_divergent_inputs():
    """The numpy fast parse must not silently accept what the reference's
    per-line Integer.parseInt would reject (floats, comments, blanks,
    overflow) — each falls back and raises, or parses identically."""
    import pytest

    from tpu_cooccurrence.io.parse import parse_lines

    ok_u, ok_i, ok_t = parse_lines(["1,2,3", "-4,5,6"])
    np.testing.assert_array_equal(ok_u, [1, -4])
    for bad in (["1.9,2,3"], ["1e3,2,3"], ["#1,2,3"], ["1,2,3", ""],
                ["1,2"], ["1,2,3,4"]):
        with pytest.raises(ValueError):
            parse_lines(bad)
    with pytest.raises((ValueError, OverflowError)):
        parse_lines(["99999999999999999999,1,2"])


def test_parse_error_carries_provenance_and_raw_line():
    """Satellite fix (ISSUE 5): every parse rejection names path:lineno
    and the offending raw line — independent of quarantine being on."""
    import pytest

    from tpu_cooccurrence.io.parse import ParseError, parse_lines

    with pytest.raises(ParseError) as ei:
        parse_lines(["1,2,3", "not-a-record", "4,5,6"],
                    provenance=[("data.csv", 10), ("data.csv", 11),
                                ("data.csv", 12)])
    err = ei.value
    assert err.source_path == "data.csv" and err.lineno == 11
    assert err.raw == "not-a-record"
    assert "data.csv:11" in str(err) and "not-a-record" in str(err)
    # Without provenance: batch-relative position against "<stream>".
    with pytest.raises(ParseError) as ei:
        parse_lines(["1,2,3", "9,9"])
    assert ei.value.source_path == "<stream>" and ei.value.lineno == 2
    # Out-of-int64-range ids are a provenance-carrying rejection too,
    # not an opaque array-conversion overflow.
    with pytest.raises(ParseError, match="out of int64 range"):
        parse_lines(["99999999999999999999,1,2"])


def test_batched_lines_captures_origin_per_line(tmp_path):
    """The batcher records (path, lineno) per buffered line from the
    source's origin hook, so a mid-batch poison line is named exactly
    (blank lines are counted in file linenos but never buffered)."""
    import pytest

    from tpu_cooccurrence.io.parse import ParseError, batched_lines
    from tpu_cooccurrence.io.source import FileMonitorSource

    p = tmp_path / "in.csv"
    p.write_text("1,2,3\n\n4,5,6\nBAD\n7,8,9\n")
    src = FileMonitorSource(str(p))
    with pytest.raises(ParseError) as ei:
        list(batched_lines(src.lines(), origin=src.origin))
    assert ei.value.source_path == str(p)
    assert ei.value.lineno == 4  # raw file lineno, blank line included
    assert ei.value.raw == "BAD"


# -- in-flight rewrite guard (ISSUE 18) --------------------------------


class RecordingQuarantine:
    def __init__(self):
        self.records = []

    def quarantine(self, path, lineno, raw, reason):
        self.records.append((path, lineno, raw, reason))


def test_inflight_guard_resumes_after_append_with_new_mtime(tmp_path):
    """Append-only growth moves the mtime, but the guard (size +
    head-prefix hash) proves the consumed prefix is intact: resume at
    the exact line instead of the legacy whole-file re-read."""
    f = tmp_path / "a.csv"
    f.write_text("l1\nl2\nl3\nl4\n")
    src = FileMonitorSource(str(f))
    it = src.lines()
    assert [next(it) for _ in range(2)] == ["l1", "l2"]
    state = src.checkpoint_state()
    offsets = src.offsets_state()

    with open(f, "a") as fh:
        fh.write("l5\nl6\n")  # mtime moves; prefix untouched
    src2 = FileMonitorSource(str(f))
    src2.restore_state(state)
    src2.restore_offsets(offsets)
    assert list(src2.lines()) == ["l3", "l4", "l5", "l6"]


def test_inflight_guard_dead_letters_rewritten_file(tmp_path):
    """A rewritten in-flight file (same length, different bytes) is
    dead-lettered and skipped — its prefix is NOT double-counted into
    still-open windows, and later files still flow."""
    a, b = tmp_path / "a.csv", tmp_path / "b.csv"
    a.write_text("a1\na2\na3\n")
    b.write_text("b1\nb2\n")
    t = os.stat(a).st_mtime_ns
    os.utime(b, ns=(t + 1000, t + 1000))  # b strictly newer

    src = FileMonitorSource(str(tmp_path))
    it = src.lines()
    assert [next(it) for _ in range(2)] == ["a1", "a2"]
    state = src.checkpoint_state()
    offsets = src.offsets_state()

    a.write_text("x1\nx2\nx3\n")  # rewrite: same size, new content
    src2 = FileMonitorSource(str(tmp_path))
    events = []
    q = RecordingQuarantine()
    src2.attach(quarantine=q, on_event=events.append)
    src2.restore_state(state)
    src2.restore_offsets(offsets)
    got = list(src2.lines())
    assert got == ["b1", "b2"]  # nothing from a.csv, old or new
    assert events == ["ingest/file-rewritten:a.csv"]
    assert q.records and "rewritten" in q.records[0][3]
    assert q.records[0][0] == str(a)


def test_inflight_guard_shrunk_file_is_rewritten(tmp_path):
    f = tmp_path / "a.csv"
    f.write_text("l1\nl2\nl3\nl4\n")
    src = FileMonitorSource(str(f))
    it = src.lines()
    [next(it) for _ in range(3)]
    state, offsets = src.checkpoint_state(), src.offsets_state()

    f.write_text("l1\n")  # shrunk below the consumed prefix
    src2 = FileMonitorSource(str(f))
    events = []
    src2.attach(on_event=events.append)
    src2.restore_state(state)
    src2.restore_offsets(offsets)
    assert list(src2.lines()) == []
    assert events == ["ingest/file-rewritten:a.csv"]


def test_legacy_restore_keeps_mtime_rule(tmp_path):
    """A checkpoint without the offsets section (markers only) keeps
    the pre-guard behavior: resume on an unchanged mtime, re-read the
    whole file when the mtime moved — the exposure the guard closes,
    preserved for legacy snapshots rather than silently skipping."""
    f = tmp_path / "a.csv"
    f.write_text("l1\nl2\nl3\n")
    src = FileMonitorSource(str(f))
    it = src.lines()
    [next(it) for _ in range(2)]
    state = src.checkpoint_state()

    # Unchanged mtime: marker-exact resume.
    src2 = FileMonitorSource(str(f))
    src2.restore_state(state)
    assert list(src2.lines()) == ["l3"]

    # Touched (mtime moved, content identical): legacy re-read whole.
    now_ns = os.stat(f).st_mtime_ns + 10_000_000
    os.utime(f, ns=(now_ns, now_ns))
    src3 = FileMonitorSource(str(f))
    src3.restore_state(state)
    assert list(src3.lines()) == ["l1", "l2", "l3"]


def test_same_mtime_sibling_sweep(tmp_path):
    """Checkpoint/restore (markers + guard) at EVERY position across
    two files sharing mtime_ns — including k=3, a restore taken exactly
    between the two files — never re-reads or drops a line."""
    a, b = tmp_path / "a.csv", tmp_path / "b.csv"
    a.write_text("a1\na2\na3\n")
    b.write_text("b1\nb2\nb3\n")
    t = os.stat(a).st_mtime_ns
    os.utime(a, ns=(t, t))
    os.utime(b, ns=(t, t))  # identical mtime: the sort is the order
    full = ["a1", "a2", "a3", "b1", "b2", "b3"]
    assert list(FileMonitorSource(str(tmp_path)).lines()) == full

    for k in range(len(full) + 1):
        src = FileMonitorSource(str(tmp_path))
        it = src.lines()
        got = [next(it) for _ in range(k)]
        assert got == full[:k], k
        state, offsets = src.checkpoint_state(), src.offsets_state()
        src2 = FileMonitorSource(str(tmp_path))
        src2.restore_state(state)
        src2.restore_offsets(offsets)
        assert got + list(src2.lines()) == full, k
