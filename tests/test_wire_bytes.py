"""Per-window wire-byte accounting (VERDICT r3, Next #3).

On the tunneled chip (and DCN hosts generally) transfer volume is wall
time. These tests pin the steady-state transfer contract against the
observability ledger, so a stray blocking fetch (a `np.asarray` of a
device buffer inside process_window) or an uplink-size regression fails
CI instead of silently doubling tunnel time:

* deferred sparse window  = aggregated-delta uplink ONLY, zero downlink
* flush                   = dirty rows only, one exact-bytes gather
* pipelined (emit) window = one packed result fetch per scored chunk

Reference: the serialization boundaries being replaced,
FlinkCooccurrences.java:89-167 (every keyBy/broadcast hop).
"""

import numpy as np
import pytest

from tpu_cooccurrence.observability import LEDGER
from tpu_cooccurrence.ops.aggregate import aggregate_window_coo
from tpu_cooccurrence.ops.device_scorer import (DeviceScorer, pad_pow2,
                                                pad_pow4)
from tpu_cooccurrence.sampling.reservoir import PairDeltaBatch
from tpu_cooccurrence.state.sparse_scorer import (SparseDeviceScorer,
                                                  bucket_r, fixed_block)


@pytest.fixture(autouse=True)
def _reset_ledger():
    LEDGER.reset()
    yield
    LEDGER.reset()


def _pairs(seed=5, n=8000, items=256):
    rng = np.random.default_rng(seed)
    src = rng.integers(0, items, n).astype(np.int64)
    dst = rng.integers(0, items, n).astype(np.int64)
    keep = src != dst
    return PairDeltaBatch(src[keep], dst[keep],
                          np.ones(int(keep.sum()), dtype=np.int32))


def _expected_update_bytes(pairs):
    """upd [2, pad_pow4(n, 4096)] int32 + bounds [2] int32, where n =
    new cells (0 in steady state) + aggregated cells + distinct rows."""
    src_d, _dst, _val, d_key = aggregate_window_coo(
        pairs.src, pairs.dst, pairs.delta.astype(np.int64),
        return_key=True)
    n_cells = len(d_key)
    n_rows = len(np.unique(src_d))
    n_pad = pad_pow4(n_cells + n_rows, minimum=1 << 12)
    return 2 * 4 * n_pad + 8


def _expected_window_meta_bytes(scorer):
    """meta_all [3, sum(S)] int32 over the monotone fixed-shape plan."""
    min_r = max(16, scorer.top_k)
    total = 0
    for b, n_chunks in scorer._plan_buckets.items():
        R = bucket_r(b, min_r, scorer.score_ladder)
        total += n_chunks * fixed_block(R, scorer.FIXED_BUDGET,
                                        scorer.FIXED_ROW_CAP)
    return 3 * 4 * total


def test_deferred_sparse_steady_window_uplink_only():
    """Steady state (no new cells, no moves, no plan growth): exactly one
    update upload + one meta upload, ZERO downlink."""
    pairs = _pairs()
    sc = SparseDeviceScorer(5, defer_results=True, fixed_shapes=True)
    sc.process_window(0, pairs)  # warmup: allocs, moves, plan discovery

    LEDGER.reset()
    sc.process_window(10, pairs)  # same cells: pure steady state
    assert LEDGER.labels("d2h") == [], (
        "a deferred window must fetch NOTHING from the device")
    assert LEDGER.labels("h2d") == ["update", "window-meta"]
    up_b, meta_b = [e.nbytes for e in LEDGER.events]
    assert up_b == _expected_update_bytes(pairs)
    assert meta_b == _expected_window_meta_bytes(sc)


def test_deferred_flush_fetches_dirty_rows_only():
    pairs = _pairs()
    sc = SparseDeviceScorer(5, defer_results=True, fixed_shapes=True)
    sc.process_window(0, pairs)
    n_dirty = int(sc._results.dirty.sum())
    assert n_dirty > 0

    LEDGER.reset()
    batch = sc.flush()
    assert len(batch.rows) == n_dirty
    rows_pad = pad_pow2(n_dirty, minimum=16)
    assert LEDGER.labels("h2d") == ["drain-rows"]
    assert LEDGER.labels("d2h") == ["results-drain"]
    up, down = LEDGER.events
    assert up.nbytes == 4 * rows_pad
    assert down.nbytes == 2 * rows_pad * sc.top_k * 4

    # Nothing new scored: a second flush moves zero bytes.
    LEDGER.reset()
    assert len(sc.flush().rows) == 0
    assert all(v == 0 for v in LEDGER.summary().values())


def test_deferred_idle_window_moves_nothing():
    sc = SparseDeviceScorer(5, defer_results=True, fixed_shapes=True)
    sc.process_window(0, _pairs())
    LEDGER.reset()
    sc.process_window(10, PairDeltaBatch(np.zeros(0, np.int64),
                                         np.zeros(0, np.int64),
                                         np.zeros(0, np.int32)))
    assert LEDGER.summary()["h2d_calls"] == 0
    assert LEDGER.summary()["d2h_calls"] == 0


def test_pipelined_sparse_window_fetches_packed_results_once():
    """The emit-updates path fetches exactly the packed [2, S, K] blocks
    of the PREVIOUS window (one-deep pipeline), nothing else."""
    pairs = _pairs()
    sc = SparseDeviceScorer(5, defer_results=False)
    sc.process_window(0, pairs)   # fills the pipeline
    LEDGER.reset()
    sc.process_window(10, pairs)  # steady: uplink + drain of window 0
    down = LEDGER.labels("d2h")
    assert down and set(down) == {"results"}
    up = LEDGER.labels("h2d")
    assert up[0] == "update"
    assert set(up[1:]) == {"bucket-meta"}


def test_deferred_dense_steady_window_uplink_only():
    pairs = _pairs(items=128)
    sc = DeviceScorer(128, 5, defer_results=True)
    sc.process_window(0, pairs)
    LEDGER.reset()
    sc.process_window(10, pairs)
    assert LEDGER.labels("d2h") == []
    up = LEDGER.labels("h2d")
    assert set(up) == {"coo", "score-rows"}
    # uplink bytes: one packed [3, pad] COO block (u16 at this vocab)
    # + one padded score-rows vector.
    src, _dst, agg = aggregate_window_coo(pairs.src, pairs.dst, pairs.delta)
    coo_pad = pad_pow2(len(src), minimum=1 << 14)
    rows = len(np.unique(src))
    rows_pad = min(pad_pow4(rows, minimum=64), sc.max_score_rows)
    assert LEDGER.h2d_bytes == 3 * 2 * coo_pad + 4 * rows_pad
