"""PROCESS_CONTINUOUSLY end to end: the reference's tail-the-directory
mode (ContinuousFileMonitoringFunction.java:204-236) driven through the
real CLI — files appearing over time are picked up by modification
time, their events advance the watermark (firing earlier windows), and
updated rows stream out while the process keeps running.
"""

import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ENV = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")


def _write(path, items, ts0, n=400, seed=1, mtime_ns=None):
    """Write under a hidden temp name, set mtime, then rename into the
    watched directory: the CLI's monitor polls concurrently, and a file
    observed mid-write (or before the utime backdate) would advance the
    monitor's mtime marker past the final mtime and lose the file.
    Hidden names (leading '.') are excluded from listing."""
    rng = np.random.default_rng(seed)
    ts = ts0 + np.cumsum(rng.integers(0, 3, n))
    path = str(path)
    tmp = os.path.join(os.path.dirname(path),
                       "." + os.path.basename(path) + ".tmp")
    with open(tmp, "w") as f:
        for u, i, t in zip(rng.integers(0, 30, n),
                           rng.choice(items, n), ts):
            f.write(f"{u},{i},{t}\n")
    if mtime_ns is not None:
        os.utime(tmp, ns=(mtime_ns, mtime_ns))
    os.rename(tmp, path)
    return int(ts[-1])


class _Reader:
    """Collects a process's stdout lines on a thread."""

    def __init__(self, proc):
        self.lines = []
        self._t = threading.Thread(target=self._pump, args=(proc,),
                                   daemon=True)
        self._t.start()

    def _pump(self, proc):
        for line in proc.stdout:
            self.lines.append(line.rstrip("\n"))

    def wait_for(self, pred, timeout_s=90.0):
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if any(pred(ln) for ln in list(self.lines)):
                return True
            time.sleep(0.2)
        return False


@pytest.mark.slow
def test_process_continuously_picks_up_new_files(tmp_path):
    d = tmp_path / "stream"
    d.mkdir()
    end1 = _write(d / "a.csv", items=np.arange(100, 120), ts0=0,
                  mtime_ns=1_000_000_000)

    proc = subprocess.Popen(
        [sys.executable, "-m", "tpu_cooccurrence.cli",
         "-i", str(d), "-ws", "100", "-ic", "20", "-uc", "8",
         "-s", "0xC0FFEE", "--backend", "oracle",
         "--process-continuously", "--emit-updates", "-bt", "100"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env=ENV, cwd=REPO)
    try:
        reader = _Reader(proc)
        # Phase 1: file a's early windows fire (its own later events
        # advance the watermark) and rows stream while the job runs.
        assert reader.wait_for(lambda ln: ln.startswith("1")), (
            "no rows emitted from the initial file")
        assert proc.poll() is None, "continuous job exited on its own"

        # Phase 2: a NEW file with a newer mtime and later timestamps —
        # the monitor must pick it up, and its items must appear.
        _write(d / "b.csv", items=np.arange(500, 520), ts0=end1 + 1,
               seed=2, mtime_ns=2_000_000_000)
        assert reader.wait_for(lambda ln: ln.split("\t")[0].startswith("5")), (
            "rows from the appended file never streamed")
        assert proc.poll() is None
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=30)


@pytest.mark.slow
def test_process_continuously_ignores_old_mtime(tmp_path):
    """A file whose mtime is NOT newer than the max seen is never
    re-forwarded (the reference's global_modification_time contract)."""
    from tpu_cooccurrence.io.source import FileMonitorSource

    d = tmp_path / "stream"
    d.mkdir()
    _write(d / "a.csv", items=np.arange(100, 110), ts0=0, n=50,
           mtime_ns=5_000_000_000)
    src = FileMonitorSource(str(d), process_continuously=True,
                            poll_interval_s=0.01)
    it = src.lines()
    got = []
    while True:
        ln = next(it)
        if ln is None:  # idle heartbeat: first listing exhausted
            break
        got.append(ln)
    assert len(got) == 50
    # An "older" file appearing later (mtime below the marker): ignored.
    _write(d / "b.csv", items=np.arange(200, 210), ts0=999, n=10,
           mtime_ns=4_000_000_000)
    for _ in range(3):
        assert next(it) is None  # nothing but heartbeats
    # A genuinely newer file: consumed.
    _write(d / "c.csv", items=np.arange(300, 310), ts0=2000, n=10,
           mtime_ns=6_000_000_000)
    new = []
    while len(new) < 10:
        ln = next(it)
        if ln is not None:
            new.append(ln)
    assert all(int(ln.split(",")[1]) >= 300 for ln in new)
