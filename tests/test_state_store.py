"""Elastic state (state/store.py): tiered spill cache + rescale-on-restore.

The contracts under test:

* **Spill transparency** — a spill-enabled run is bit-identical to
  spill-off on the same stream: same rows, same scores, same tie order
  (within-row slab order is preserved across the spill/promote round
  trip), and its checkpoint blobs are byte-identical (the arena merges
  back into the canonical format at save).
* **Store interchange** — :class:`DirectSlabStore` and
  :class:`TieredSlabStore` round-trip the same canonical blob; a
  checkpoint written by either restores under the other.
* **Rescale-on-restore** — :class:`ShardedRescaleStore` re-buckets a
  ``--num-shards N`` checkpoint onto M shards, N→M in both directions,
  bit-identical to resuming at N (the same-topology resume is the
  reference: any restore rebuilds rows in key order, so THAT is the
  canonical post-restore state).
* **Pre-codec compatibility** — a PR-7 ``ckpt_codec``-less checkpoint
  (``--wire-format raw``) restores bit-identically under
  ``TPU_COOC_ROW_INDEX=bitmap`` and under the tiered store.
"""

import os

import numpy as np
import pytest

from tpu_cooccurrence.config import Backend, Config
from tpu_cooccurrence.job import CooccurrenceJob
from tpu_cooccurrence.state.sparse_scorer import (HashSlabIndex, SlabIndex,
                                                  SparseDeviceScorer)
from tpu_cooccurrence.state.store import (DirectSlabStore, ShardedRescaleStore,
                                          SpillArena, TieredSlabStore,
                                          make_store, rebucket_cells)

from test_pipeline import random_stream


def assert_latest_identical(a, b):
    """EXACT equality, tie order included — the spill-transparency bar
    (stricter than test_pipeline.assert_latest_equal, which canonicalizes
    tie order away)."""
    sa, sb = a.snapshot(), b.snapshot()
    assert set(sa) == set(sb)
    for item in sa:
        assert sa[item] == sb[item], (item, sa[item], sb[item])


def sparse_cfg(tmp_path=None, **kw):
    kw.setdefault("backend", Backend.SPARSE)
    kw.setdefault("window_size", 10)
    kw.setdefault("seed", 0xABCD)
    kw.setdefault("item_cut", 5)
    kw.setdefault("user_cut", 3)
    kw.setdefault("development_mode", True)
    if tmp_path is not None:
        kw.setdefault("checkpoint_dir", str(tmp_path / "ckpt"))
    return Config(**kw)


def run_job(cfg, users, items, ts, chunk=97):
    job = CooccurrenceJob(cfg)
    for lo in range(0, len(users), chunk):
        job.add_batch(users[lo:lo + chunk], items[lo:lo + chunk],
                      ts[lo:lo + chunk])
    job.finish()
    return job


SPILL = dict(spill_threshold_windows=2, spill_target_hbm_frac=0.0)


# -- spill transparency ------------------------------------------------


@pytest.mark.parametrize("depth", [0, 2])
def test_spill_bit_identical_to_off(depth):
    users, items, ts = random_stream(77, n=800, n_items=60, n_users=25)
    # dev-mode off: the row-sum invariant is separately covered, and
    # the point here is exact OUTPUT equality, cheap enough for tier-1.
    off = run_job(sparse_cfg(pipeline_depth=depth,
                             development_mode=False), users, items, ts)
    on = run_job(sparse_cfg(pipeline_depth=depth, development_mode=False,
                            **SPILL), users, items, ts)
    assert_latest_identical(off.latest, on.latest)
    assert off.counters.as_dict() == on.counters.as_dict()
    store = on.scorer.store
    assert isinstance(store, TieredSlabStore)
    assert store.evictions > 0, "stream never spilled — test is vacuous"
    assert store.promotions > 0, "nothing re-promoted — test is vacuous"
    assert isinstance(off.scorer.store, DirectSlabStore)


def test_spill_checkpoint_blob_byte_identical():
    """The CANONICAL blob arrays stay byte-identical to spill-off;
    the tiered store adds only the supplemental ``tier_*`` recency
    arrays (ISSUE 12 satellite — restore resumes the same residency
    trajectory), which every other store ignores."""
    users, items, ts = random_stream(78, n=700, n_items=60, n_users=25)
    off = run_job(sparse_cfg(), users, items, ts)
    on = run_job(sparse_cfg(**SPILL), users, items, ts)
    assert len(on.scorer.store.arena) > 0, "nothing left spilled at end"
    a = off.scorer.checkpoint_state()
    b = on.scorer.checkpoint_state()
    extra = set(b) - set(a)
    assert extra == {"tier_clock", "tier_rows", "tier_ages"}
    for key in a:
        assert np.array_equal(a[key], b[key]), key
    # The persisted clock matches the run's fired-window count and the
    # stamp arrays are consistent.
    assert int(b["tier_clock"][0]) == on.scorer.store.clock
    assert len(b["tier_rows"]) == len(b["tier_ages"])
    assert (b["tier_ages"] >= 0).all()


def test_spill_resume_bit_identical(tmp_path):
    users, items, ts = random_stream(79, n=800, n_items=60, n_users=25)
    half = 390
    ref = run_job(sparse_cfg(tmp_path / "ref", **SPILL), users, items, ts)

    a = CooccurrenceJob(sparse_cfg(tmp_path, **SPILL))
    a.add_batch(users[:half], items[:half], ts[:half])
    a.checkpoint()
    assert len(a.scorer.store.arena) > 0  # the blob really merged spill

    # Resume under the OTHER store kind too: blobs are interchangeable.
    for resume_kw in (SPILL, {}):
        b = CooccurrenceJob(sparse_cfg(tmp_path, **resume_kw))
        b.restore()
        b.add_batch(users[half:], items[half:], ts[half:])
        b.finish()
        # Reference: a spill-on run RESTORED at the same point (restore
        # canonicalizes within-row order, so the uninterrupted run is
        # not the bit-exact comparator — the restored one is).
        c = CooccurrenceJob(sparse_cfg(tmp_path, **SPILL))
        c.restore()
        c.add_batch(users[half:], items[half:], ts[half:])
        c.finish()
        assert_latest_identical(c.latest, b.latest)
    assert set(ref.latest.snapshot()) == set(b.latest.snapshot())


def test_spill_parity_across_restore(tmp_path):
    """Recency is checkpointed (ISSUE 12 satellite): a restored tiered
    run resumes the writer's spill clock, so residency converges to the
    uninterrupted run's at the first post-restore tick instead of every
    row sitting hot for ``threshold`` more windows."""
    users, items, ts = random_stream(83, n=900, n_items=70, n_users=26)
    half = 430
    a = CooccurrenceJob(sparse_cfg(tmp_path, **SPILL))
    a.add_batch(users[:half], items[:half], ts[:half])
    a.checkpoint()
    store_a = a.scorer.store
    assert store_a.clock > 0 and len(store_a.arena), "vacuous setup"
    b = CooccurrenceJob(sparse_cfg(tmp_path, **SPILL))
    b.restore()
    store_b = b.scorer.store

    def eligibility(store):
        # Ages are persisted clipped at the threshold (the same
        # collapse the tick's bucket consolidation applies), so the
        # restored trajectory is compared in eligibility space.
        lt = store.last_touch
        return np.where(lt >= 0,
                        np.minimum(store.clock - lt, store.threshold),
                        -1)

    # The clock resumed (not reset to 0) and the stamps match the
    # writer's exactly up to the documented eligible-age collapse.
    assert store_b.clock == store_a.clock
    np.testing.assert_array_equal(eligibility(store_b),
                                  eligibility(store_a))
    # Continue both; the arena's resident set re-converges and stays in
    # lockstep (with frac=0.0 every eligible row spills each tick).
    a.add_batch(users[half:], items[half:], ts[half:])
    a.finish()
    b.add_batch(users[half:], items[half:], ts[half:])
    b.finish()
    assert sorted(store_b.arena.dir) == sorted(store_a.arena.dir)
    assert store_b.clock == store_a.clock
    assert set(a.latest.snapshot()) == set(b.latest.snapshot())


def _phased_stream():
    """Three phases over disjoint-ish item sets so rows genuinely go
    cold: (1) a tiny hot set driven past the int8 promotion bound,
    (2) several windows of fresh users on OTHER items (phase-1 rows —
    including WIDE ones — idle long enough to spill), (3) phase-1 items
    re-touched (wide rows re-promote out of the arena)."""
    rng = np.random.default_rng(82)
    us, its, tss = [], [], []
    t0 = 0

    def phase(user_base, item_lo, item_hi, windows, per):
        nonlocal t0
        for _w in range(windows):
            us.append(user_base + rng.integers(0, 4, per))
            its.append(rng.integers(item_lo, item_hi, per))
            tss.append(np.full(per, t0, dtype=np.int64) + np.arange(per) % 10)
            t0 += 10
    phase(0, 0, 6, 10, 80)       # hot head, counts pile past 127
    phase(100, 6, 30, 8, 40)     # fresh users, other items: head goes cold
    phase(200, 0, 6, 3, 40)      # head re-touched: promote from arena
    return (np.concatenate(us), np.concatenate(its),
            np.concatenate(tss))


def test_spill_narrow_wide_residency_and_gauges():
    """Rows pushed past the int8 promotion bound spill out of and
    re-promote into the wide table; spill-on stays bit-identical and
    the registry gauges move."""
    from tpu_cooccurrence.observability.registry import REGISTRY

    users, items, ts = _phased_stream()
    kw = dict(cell_dtype="int8", skip_cuts=True)
    off = run_job(sparse_cfg(**kw), users, items, ts)
    REGISTRY.reset()
    on = run_job(sparse_cfg(**kw, **SPILL), users, items, ts)
    assert_latest_identical(off.latest, on.latest)
    assert on.scorer.wide_rows.any(), "nothing promoted wide — vacuous"
    assert on.scorer.store.evictions > 0
    assert on.scorer.store.promotions > 0
    assert REGISTRY.gauge("cooc_spill_evictions_total").get() > 0
    assert REGISTRY.gauge("cooc_spill_row_touches_total").get() > 0
    assert (REGISTRY.gauge("cooc_spill_promotions_total").get()
            == on.scorer.store.promotions)


def test_spill_cross_promotion_window_tie_order_identical():
    """A spilled NARROW row whose sum crosses the wide bound on its
    re-promotion window must adopt its cells in KEY order — the
    spill-off reference for that window is ``_promote_rows``, whose
    wide insert is key-sorted. Arena (narrow slab) order would flip
    slot-ordered tie-breaks (regression: tied partners emitted [9, 2]
    vs spill-off's [2, 9])."""
    from tpu_cooccurrence.sampling.reservoir import PairDeltaBatch

    def scorer(**kw):
        return SparseDeviceScorer(
            5, cell_dtype="int8", wire_format="raw",
            development_mode=True, capacity=64, items_capacity=8,
            compact_min_heap=256, **kw)

    def feed(sc):
        # Row 5 collects tied partners 9 then 2 (slab order [9, 2], key
        # order [2, 9]), idles two windows (spills under threshold 1),
        # then re-touches with a delta crossing the int8 bound (128) —
        # promotion to wide happens ON the re-promotion window.
        windows = [
            ([5, 9, 20, 21], [9, 5, 21, 20], [1, 1, 1, 1]),
            ([5, 2, 20, 21], [2, 5, 21, 20], [1, 1, 1, 1]),
            ([20, 21], [21, 20], [1, 1]),
            ([20, 21], [21, 20], [1, 1]),
            ([5, 60, 20, 21], [60, 5, 21, 20], [126, 126, 1, 1]),
        ]
        outs = []
        for w, (s, d, v) in enumerate(windows):
            outs.append(sc.process_window(
                w * 10, PairDeltaBatch(np.asarray(s, np.int64),
                                       np.asarray(d, np.int64),
                                       np.asarray(v, np.int32))))
        outs.append(sc.flush())
        return outs

    off = feed(scorer())
    on_sc = scorer(spill_threshold_windows=1, spill_target_hbm_frac=0.0)
    on = feed(on_sc)
    assert on_sc.store.promotions > 0, "row 5 never spilled — vacuous"
    assert bool(on_sc.wide_rows[5]), "row 5 never crossed the bound"
    for a, b in zip(off, on):
        oa, ob = np.argsort(a.rows), np.argsort(b.rows)
        np.testing.assert_array_equal(a.rows[oa], b.rows[ob])
        np.testing.assert_array_equal(a.idx[oa], b.idx[ob])
        np.testing.assert_array_equal(a.vals[oa], b.vals[ob])


# -- adopt_rows: the order-preservation core ---------------------------


@pytest.mark.parametrize("index_cls", [SlabIndex, HashSlabIndex])
def test_adopt_rows_preserves_slab_order(index_cls):
    try:
        ix = index_cls()
    except RuntimeError:
        pytest.skip("native library unavailable")
    # Insert a row's cells over two windows so within-row slab order is
    # chronological, NOT key order.
    k = lambda r, d: (r << 32) | d
    ix.apply(np.asarray(sorted([k(5, 9), k(5, 30)]), dtype=np.int64))
    ix.apply(np.asarray(sorted([k(5, 2), k(5, 11)]), dtype=np.int64))
    rows = np.asarray([5], dtype=np.int64)
    keys, slots = ix.row_cells(rows)
    order = np.argsort(slots, kind="stable")
    slab_order_keys = keys[order].copy()
    assert list(slab_order_keys & 0xFFFFFFFF) == [9, 30, 2, 11]
    ix.free_rows(rows)
    slots2 = ix.adopt_rows(rows, slab_order_keys,
                           np.asarray([4], dtype=np.int32))
    # Slots ascend in the given order: slab layout reproduced exactly.
    assert list(np.diff(slots2)) == [1, 1, 1]
    assert np.array_equal(ix.lookup(slab_order_keys), slots2)
    keys3, slots3 = ix.row_cells(rows)
    assert np.array_equal(keys3[np.argsort(slots3, kind="stable")],
                          slab_order_keys)


def test_lookup_rejects_absent_keys():
    ix = SlabIndex()
    ix.apply(np.asarray([(1 << 32) | 3], dtype=np.int64))
    with pytest.raises(KeyError):
        ix.lookup(np.asarray([(9 << 32) | 1], dtype=np.int64))


# -- the arena ---------------------------------------------------------


def test_spill_arena_round_trip_and_compaction():
    arena = SpillArena()
    rng = np.random.default_rng(5)
    expect = {}
    for r in range(200):
        n = int(rng.integers(1, 9))
        keys = (np.int64(r) << 32) | rng.integers(0, 1000, n).astype(np.int64)
        cnt = rng.integers(1, 100, n).astype(np.int32)
        arena.put_rows(np.asarray([r]), np.asarray([n]), keys, cnt,
                       np.asarray([r % 3 == 0]))
        expect[r] = (keys.copy(), cnt.copy(), r % 3 == 0)
    # Pop half (forces compaction), verify payloads, re-add some.
    for r in range(0, 200, 2):
        lens, keys, cnt, wide = arena.pop_rows(np.asarray([r]))
        ek, ec, ew = expect.pop(r)
        assert np.array_equal(keys, ek) and np.array_equal(cnt, ec)
        assert wide[0] == ew and lens[0] == len(ek)
        assert r not in arena
    assert len(arena) == len(expect)
    keys_all, cnt_all = arena.all_cells()
    assert len(keys_all) == sum(len(k) for k, _c, _w in expect.values())
    arena.reset()
    assert len(arena) == 0 and arena.live_cells == 0


def test_tiered_bucket_directory_stays_bounded():
    """Long under-target streams must not grow one recency bucket per
    window: once the directory crosses the amortization bound the
    eligible tail consolidates at the eligibility horizon."""
    scorer = SparseDeviceScorer(top_k=5)
    store = TieredSlabStore(scorer, 2, 1.0)  # frac 1.0: never over target
    rows = np.arange(4, dtype=np.int64)
    for w in range(300):
        store.tick()
        # Touch a rotating single row so older stamps go stale slowly.
        store.promote_touched(rows[w % 4: w % 4 + 1])
    assert len(store._buckets) <= max(4 * store.threshold, 64) + 2
    assert store.evictions == 0  # never over target -> never spilled


# -- store interface / blob interchange --------------------------------


def test_make_store_kinds():
    scorer = SparseDeviceScorer(top_k=5)
    assert isinstance(make_store(scorer, 0, 0.5), DirectSlabStore)
    tiered = make_store(scorer, 3, 0.25)
    assert isinstance(tiered, TieredSlabStore)
    assert tiered.tiered and not DirectSlabStore(scorer).tiered
    with pytest.raises(ValueError):
        TieredSlabStore(scorer, 0)
    with pytest.raises(ValueError):
        TieredSlabStore(scorer, 2, 1.5)


def test_direct_store_round_trip_matches_scorer():
    users, items, ts = random_stream(83, n=500, n_items=40, n_users=20)
    job = run_job(sparse_cfg(), users, items, ts)
    blob = job.scorer.store.checkpoint_state()
    fresh = SparseDeviceScorer(top_k=job.config.top_k,
                               cell_dtype=job.scorer.cell_dtype)
    fresh.store.restore_state(blob)
    blob2 = fresh.store.checkpoint_state()
    for key in blob:
        assert np.array_equal(blob[key], blob2[key]), key


# -- rescale-on-restore ------------------------------------------------


def test_rebucket_cells_partitions_exactly():
    rng = np.random.default_rng(9)
    rows = rng.integers(0, 500, 300).astype(np.int64)
    dst = rng.integers(0, 500, 300).astype(np.int64)
    keys = np.unique((rows << 32) | dst)
    vals = rng.integers(1, 50, len(keys)).astype(np.int64)
    for d_count in (1, 2, 4, 8):
        parts = rebucket_cells(keys, vals, d_count)
        assert len(parts) == d_count
        total = 0
        for d, (lk, v, dst_d) in enumerate(parts):
            assert np.all(np.diff(lk) > 0)  # sorted unique local keys
            recon = ((((lk >> 32) * d_count + d) << 32)
                     | (lk & 0xFFFFFFFF))
            assert np.all(np.isin(recon, keys))
            assert np.array_equal(dst_d, recon & 0xFFFFFFFF)
            total += len(lk)
        assert total == len(keys)


@pytest.mark.parametrize("n_from,n_to", [
    (2, 4), (4, 2),
    # Non-divisible topology: the modulo re-bucket owes nothing to
    # divisibility (the load-driven autoscaler may land on any size
    # inside its min/max bounds).
    (2, 3), (3, 2),
    # Degenerate single-shard ends: a 1-shard checkpoint is the
    # single-device SparseDeviceScorer's global blob (interchangeable
    # by design), restored onto a mesh — and back down to one shard.
    (1, 4), (4, 1),
])
def test_sharded_rescale_restore_bit_identical(tmp_path, n_from, n_to):
    """A checkpoint taken at N shards resumes at M bit-identically to
    resuming at N — the ShardedRescaleStore re-bucket is pure topology,
    zero content change."""
    import shutil

    users, items, ts = random_stream(31, n=500, n_items=60, n_users=25)
    half = 240

    def cfg(path, shards):
        return Config(window_size=10, seed=0xBEEF, item_cut=5, user_cut=3,
                      backend=Backend.SPARSE, num_shards=shards,
                      checkpoint_dir=str(path))

    a = CooccurrenceJob(cfg(tmp_path / "ck", n_from))
    if n_from > 1:
        assert isinstance(a.scorer.store, ShardedRescaleStore)
    a.add_batch(users[:half], items[:half], ts[:half])
    a.checkpoint()
    shutil.copytree(tmp_path / "ck", tmp_path / "ck2")

    same = CooccurrenceJob(cfg(tmp_path / "ck2", n_from))
    same.restore()
    same.add_batch(users[half:], items[half:], ts[half:])
    same.finish()

    rescaled = CooccurrenceJob(cfg(tmp_path / "ck", n_to))
    rescaled.restore()
    rescaled.add_batch(users[half:], items[half:], ts[half:])
    rescaled.finish()

    assert_latest_identical(same.latest, rescaled.latest)
    assert same.counters.as_dict() == rescaled.counters.as_dict()


# -- pre-codec checkpoints under the new store / index ------------------


@pytest.mark.parametrize("resume_kw", [
    {},                 # bitmap row index (the default), direct store
    SPILL,              # TieredSlabStore
], ids=["bitmap", "tiered"])
def test_precodec_checkpoint_restores_bit_identical(tmp_path, monkeypatch,
                                                    resume_kw):
    """A PR-7 pre-codec checkpoint (--wire-format raw writes the
    ckpt_codec-less layout) restores under TPU_COOC_ROW_INDEX=bitmap and
    under the TieredSlabStore, resuming bit-identically either way."""
    monkeypatch.setenv("TPU_COOC_ROW_INDEX", "bitmap")
    users, items, ts = random_stream(84, n=700, n_items=60, n_users=25)
    half = 330

    a = CooccurrenceJob(sparse_cfg(tmp_path, wire_format="raw"))
    a.add_batch(users[:half], items[:half], ts[:half])
    a.checkpoint()
    # Really pre-codec: no packed blobs, no codec record in the meta.
    import json

    gen = tmp_path / "ckpt" / "state.1.npz"
    with np.load(gen) as data:
        names = set(data.files)
        meta = json.loads(bytes(data["meta_json"]).decode())
    assert not any(n.endswith("__packed") for n in names)
    assert "ckpt_codec" not in meta

    b = CooccurrenceJob(sparse_cfg(tmp_path, wire_format="raw",
                                   **resume_kw))
    b.restore()
    b.add_batch(users[half:], items[half:], ts[half:])
    b.finish()

    # Reference: the same restore WITHOUT the new machinery (direct
    # store, same raw format) — the new store/index must change nothing.
    c = CooccurrenceJob(sparse_cfg(tmp_path, wire_format="raw"))
    c.restore()
    c.add_batch(users[half:], items[half:], ts[half:])
    c.finish()
    assert_latest_identical(c.latest, b.latest)
    assert c.counters.as_dict() == b.counters.as_dict()


# -- config gating -----------------------------------------------------


def test_spill_flags_config_gating():
    with pytest.raises(ValueError):
        Config(window_size=10, spill_threshold_windows=-1)
    with pytest.raises(ValueError):
        Config(window_size=10, spill_target_hbm_frac=1.5)
    with pytest.raises(ValueError):  # device backend cannot spill
        Config(window_size=10, backend=Backend.DEVICE,
               spill_threshold_windows=3)
    with pytest.raises(ValueError):  # sharded sparse cannot spill
        Config(window_size=10, backend=Backend.SPARSE, num_shards=4,
               spill_threshold_windows=3)
    cfg = Config(window_size=10, backend=Backend.SPARSE,
                 spill_threshold_windows=3, spill_target_hbm_frac=0.25)
    assert cfg.spill_threshold_windows == 3


def test_checkpoint_retain_sweeps_aged_corrupt_files(tmp_path):
    """--checkpoint-retain ages out orphan *.corrupt quarantine files
    beyond the retain window (they previously accumulated forever);
    a corrupt file still inside the window is kept for forensics."""
    users, items, ts = random_stream(85, n=600, n_items=40, n_users=20)
    cfg = sparse_cfg(tmp_path, backend=Backend.ORACLE,
                     checkpoint_retain=2)
    cfg.backend = Backend.ORACLE
    job = CooccurrenceJob(cfg)
    ck = tmp_path / "ckpt"
    half = len(users) // 2
    job.add_batch(users[:half], items[:half], ts[:half])
    job.checkpoint()   # gen 1
    # Simulate old quarantined generations (gen 0 = legacy name).
    (ck / "state.0.npz.corrupt").write_bytes(b"x")
    (ck / "state.npz.corrupt").write_bytes(b"x")
    job.checkpoint()   # gen 2
    job.checkpoint()   # gen 3: retain=2 keeps {2, 3}; corrupt 0 aged out
    names = set(os.listdir(ck))
    assert "state.2.npz" in names and "state.3.npz" in names
    assert "state.1.npz" not in names
    assert "state.0.npz.corrupt" not in names
    assert "state.npz.corrupt" not in names
    # A corrupt generation INSIDE the window survives the sweep.
    (ck / "state.3.npz.corrupt").write_bytes(b"x")
    job.checkpoint()   # gen 4: window = {3, 4}; 3.corrupt stays
    names = set(os.listdir(ck))
    assert "state.3.npz.corrupt" in names
    job.finish()
