"""Sparse device backend (HBM slab + host index) tests.

Tiny initial capacities force every structural path — heap doubling, row
relocation, compaction, items-capacity growth — on small test streams.
"""

import numpy as np
import pytest

from tpu_cooccurrence.config import Backend, Config
from tpu_cooccurrence.metrics import (
    OBSERVED_COOCCURRENCES,
    RESCORED_ITEMS,
    ROW_SUM_PROCESS_WINDOW,
)
from tpu_cooccurrence.state.sparse_scorer import SparseDeviceScorer

from test_pipeline import (
    assert_latest_close,
    random_stream,
    relabel_first_appearance,
    run_production,
)


def tiny_scorer_factory(cfg):
    from tpu_cooccurrence.job import CooccurrenceJob

    scorer = SparseDeviceScorer(cfg.top_k, development_mode=True,
                                capacity=64, items_capacity=8,
                                compact_min_heap=256)
    job = CooccurrenceJob(cfg, scorer=scorer)
    scorer.counters = job.counters
    return job


@pytest.mark.parametrize("overrides", [
    dict(skip_cuts=True),
    dict(item_cut=5, user_cut=4),
    dict(item_cut=3, user_cut=2, window_size=25),
])
def test_sparse_matches_oracle_backend(overrides):
    kw = dict(window_size=10, seed=0xBEEF, development_mode=True)
    kw.update(overrides)
    users, items, ts = random_stream(31)
    a = run_production(Config(**kw, backend=Backend.ORACLE), users, items, ts)
    b = run_production(Config(**kw, backend=Backend.SPARSE), users, items, ts)
    assert_latest_close(a.latest, b.latest)
    for name in (OBSERVED_COOCCURRENCES, ROW_SUM_PROCESS_WINDOW,
                 RESCORED_ITEMS):
        assert a.counters.get(name) == b.counters.get(name), name


def test_sparse_growth_and_compaction_paths():
    """Tiny capacities force heap doubling, relocations, and compaction
    while matching the oracle end to end."""
    from tpu_cooccurrence.job import CooccurrenceJob

    kw = dict(window_size=20, seed=0xD1, skip_cuts=True,
              development_mode=True)
    rng = np.random.default_rng(11)
    n = 3000
    users = relabel_first_appearance(rng.integers(0, 10, n))
    items = relabel_first_appearance(rng.integers(0, 150, n))
    ts = np.cumsum(rng.integers(0, 2, n)).astype(np.int64)

    a = run_production(Config(**kw, backend=Backend.ORACLE), users, items, ts)
    cfg = Config(**kw, backend=Backend.SPARSE)
    b = tiny_scorer_factory(cfg)
    for lo in range(0, n, 97):
        b.add_batch(users[lo:lo + 97], items[lo:lo + 97], ts[lo:lo + 97])
    b.finish()
    sc = b.scorer
    assert sc.capacity > 64          # heap doubled
    assert sc.items_cap > 8          # item registry grew
    assert sc.compactions > 0        # defragmentation actually ran
    assert_latest_close(a.latest, b.latest)


def test_sparse_index_invariants():
    """Host index/registry invariants after a mixed stream: sorted keys,
    in-range slots, per-row segments exactly [start, start+len)."""
    users, items, ts = random_stream(77, n=900, n_items=40)
    cfg = Config(window_size=15, seed=3, item_cut=6, user_cut=4,
                 backend=Backend.SPARSE, development_mode=True)
    job = tiny_scorer_factory(cfg)
    job.add_batch(users, items, ts)
    job.finish()
    sc = job.scorer
    idx = sc.index
    g_key, g_slot = idx.keys_and_slots()
    assert len(g_key) > 0              # the invariants below must bite
    assert np.all(np.diff(g_key) > 0)  # strictly sorted, unique
    assert len(g_slot) == len(g_key)
    rows = (g_key >> 32).astype(np.int64)
    for r in np.unique(rows):
        slots = np.sort(g_slot[rows == r])
        start, ln = idx.row_start[r], idx.row_len[r]
        assert ln == len(slots)
        np.testing.assert_array_equal(slots, np.arange(start, start + ln))
        assert ln <= idx.row_cap[r]
    assert idx.heap_end <= sc.capacity


def test_sparse_checkpoint_roundtrip(tmp_path):
    from tpu_cooccurrence.job import CooccurrenceJob

    kw = dict(window_size=10, seed=4, item_cut=5, user_cut=3,
              backend=Backend.SPARSE, checkpoint_dir=str(tmp_path / "ck"),
              development_mode=True)
    users, items, ts = random_stream(33, n=400)
    half = 180

    ref = CooccurrenceJob(Config(**kw))
    ref.add_batch(users, items, ts)
    ref.finish()

    a = CooccurrenceJob(Config(**kw))
    a.add_batch(users[:half], items[:half], ts[:half])
    a.checkpoint()
    b = CooccurrenceJob(Config(**kw))
    b.restore()
    b.add_batch(users[half:], items[half:], ts[half:])
    b.finish()

    assert set(ref.latest) == set(b.latest)
    for item in ref.latest:
        np.testing.assert_allclose(
            np.array([s for _, s in b.latest[item]]),
            np.array([s for _, s in ref.latest[item]]), rtol=1e-6, atol=1e-6)


def test_sparse_hybrid_checkpoint_interchange(tmp_path):
    """The migration path for the retired hybrid backend: a job configured
    with ``--backend hybrid`` (now the sparse alias) writes/restores the
    same canonical sparse-matrix checkpoint, in both directions."""
    from tpu_cooccurrence.job import CooccurrenceJob

    users, items, ts = random_stream(35, n=400)
    half = 200
    for first, second in [(Backend.HYBRID, Backend.SPARSE),
                          (Backend.SPARSE, Backend.HYBRID)]:
        kw = dict(window_size=10, seed=9, item_cut=5, user_cut=3,
                  checkpoint_dir=str(tmp_path / f"ck-{first.value}"),
                  development_mode=True)
        ref = CooccurrenceJob(Config(**kw, backend=second))
        ref.add_batch(users, items, ts)
        ref.finish()

        a = CooccurrenceJob(Config(**kw, backend=first))
        a.add_batch(users[:half], items[:half], ts[:half])
        a.checkpoint()
        b = CooccurrenceJob(Config(**kw, backend=second))
        b.restore()
        b.add_batch(users[half:], items[half:], ts[half:])
        b.finish()
        assert_latest_close(ref.latest, b.latest, rtol=1e-5, atol=1e-5)


def test_sparse_coordinator_requires_shards():
    import pytest

    from tpu_cooccurrence.config import Backend, Config
    from tpu_cooccurrence.job import CooccurrenceJob

    cfg = Config(window_size=10, seed=1, backend=Backend.SPARSE,
                 coordinator="127.0.0.1:1", num_processes=2, process_id=0)
    with pytest.raises(ValueError, match="num-shards"):
        CooccurrenceJob(cfg)


def test_slab_index_fuzz_against_slab_simulation():
    """Model-based fuzz: simulate the device slab (key per slot) on host
    through many windows of random cell batches, applying the exact move /
    new-cell / compaction protocol the real cnt/dst arrays get. Catches
    allocator, relocation, and compaction bugs that single-window
    invariant checks can't."""
    from tpu_cooccurrence.state.sparse_scorer import SlabIndex

    rng = np.random.default_rng(0xF00D)
    idx = SlabIndex(rows_capacity=8)
    slab = np.full(64, -1, dtype=np.int64)  # key living in each slot
    seen = set()
    for window in range(60):
        n = int(rng.integers(1, 120))
        rows = rng.integers(0, 50, n).astype(np.int64)
        # Zipf-ish partner ids; duplicates collapse via unique.
        dsts = rng.integers(0, 1 + int(rng.integers(1, 200)), n)
        d_key = np.unique((rows << 32) | dsts)
        plan = idx.apply(d_key)
        if idx.heap_end > len(slab):
            grown = np.full(max(2 * len(slab), idx.heap_end), -1,
                            dtype=np.int64)
            grown[: len(slab)] = slab
            slab = grown
        if plan.mv is not None:
            old_s, new_s, ln = plan.mv[0], plan.mv[1], plan.mv[2]
            for o, w, m in zip(old_s.tolist(), new_s.tolist(), ln.tolist()):
                if m:
                    slab[w: w + m] = slab[o: o + m]
        slab[plan.slots[plan.new_sel]] = d_key[plan.new_sel]
        seen.update(d_key.tolist())
        # Every applied key must be found at the slot the index returned...
        np.testing.assert_array_equal(slab[plan.slots], d_key)
        # ...and the whole index must agree with the simulated slab.
        np.testing.assert_array_equal(slab[idx.g_slot], idx.g_key)
        assert len(idx.g_key) == len(seen)
        if idx.needs_compaction(min_heap=64):
            gmap = idx.compact()
            new_slab = np.full(len(slab), -1, dtype=np.int64)
            new_slab[: len(gmap)] = slab[gmap]
            slab = new_slab
            np.testing.assert_array_equal(slab[idx.g_slot], idx.g_key)
    assert idx.compactions > 0, "fuzz never hit the compaction path"


@pytest.mark.parametrize("ladder", [2, 4, 16])
def test_sparse_score_ladder_equivalence(ladder, monkeypatch):
    """Every bucket-ladder base scores identically (padding is compute
    only); coarser ladders exist to cut dispatches on high-latency links."""
    monkeypatch.setenv("TPU_COOC_SCORE_LADDER", str(ladder))
    users, items, ts = random_stream(5, n=1200, n_items=80)
    cfg = Config(window_size=20, seed=9, item_cut=8, user_cut=5,
                 backend=Backend.SPARSE, development_mode=True)
    job = tiny_scorer_factory(cfg)
    job.add_batch(users, items, ts)
    job.finish()
    assert job.scorer.score_ladder == ladder
    monkeypatch.delenv("TPU_COOC_SCORE_LADDER")
    ref_cfg = Config(window_size=20, seed=9, item_cut=8, user_cut=5,
                     backend=Backend.ORACLE, development_mode=True)
    from tpu_cooccurrence.job import CooccurrenceJob

    ref = CooccurrenceJob(ref_cfg)
    ref.add_batch(users, items, ts)
    ref.finish()
    assert job.counters.as_dict() == ref.counters.as_dict()
    assert set(job.latest) == set(ref.latest)
    for item in ref.latest:
        np.testing.assert_allclose(
            [s for _, s in job.latest[item]],
            [s for _, s in ref.latest[item]], rtol=2e-4, atol=2e-4)


def test_sparse_chunked_upload_matches(monkeypatch):
    """TPU_COOC_UPLOAD_CHUNKS=K splits the per-window update upload
    into K transfers of one jitted call (the tunnel-cliff lever,
    tunnel_probe section 3/3b); results and counters are identical to
    the monolithic path and the chunked dispatch actually engages."""
    import tpu_cooccurrence.state.sparse_scorer as ss

    users, items, ts = random_stream(7, n=1500, n_items=90)
    kw = dict(window_size=15, seed=11, item_cut=6, user_cut=4,
              backend=Backend.SPARSE, development_mode=True)
    a = run_production(Config(**kw), users, items, ts)

    calls = {"chunked": 0}
    for name in ("_apply_update_chunked", "_apply_moves_update_chunked"):
        orig = getattr(ss, name)

        def counting(*args, _orig=orig, **kwargs):
            calls["chunked"] += 1
            return _orig(*args, **kwargs)

        monkeypatch.setattr(ss, name, counting)
    monkeypatch.setenv("TPU_COOC_UPLOAD_CHUNKS", "4")
    from tpu_cooccurrence.observability import LEDGER

    LEDGER.reset()
    b = run_production(Config(**kw), users, items, ts)
    assert calls["chunked"] > 0, "chunked path must actually engage"
    assert_latest_close(a.latest, b.latest)
    assert a.counters.as_dict() == b.counters.as_dict()
    # The ledger mirrors the actual transfer pattern: 4 chunk uploads
    # + 1 metadata upload per chunked window, never a monolithic one.
    up_labels = LEDGER.labels("h2d")
    assert "update-chunk" in up_labels and "update-meta" in up_labels
    assert "update" not in up_labels
    assert (up_labels.count("update-chunk")
            == 4 * up_labels.count("update-meta"))


def test_split_upload_edges(caplog):
    """Splitting declines tiny windows, uneven lengths, and k<=1 — and
    a requested-but-declined split warns once (an operator A/B-testing
    on grant time must not silently measure the monolithic path)."""
    import logging

    import tpu_cooccurrence.ops.device_scorer as ds
    from tpu_cooccurrence.ops.device_scorer import split_upload

    upd = np.zeros((2, 4096), dtype=np.int32)
    parts = split_upload(upd, 4)
    assert len(parts) == 4 and all(p.shape == (2, 1024) for p in parts)
    assert all(p.flags["C_CONTIGUOUS"] for p in parts)
    assert split_upload(upd, 1) is None
    ds._split_declined_warned = False
    with caplog.at_level(logging.WARNING, logger="tpu_cooccurrence"):
        assert split_upload(upd, 8) is None    # 512-element chunks: too small
        assert split_upload(np.zeros((2, 4098), np.int32), 4) is None  # uneven
    warnings = [r for r in caplog.records
                if "TPU_COOC_UPLOAD_CHUNKS" in r.message]
    assert len(warnings) == 1, "declined split must warn exactly once"
    assert split_upload(upd, 1) is None        # k<=1 never warns
    ds._split_declined_warned = False


def test_split_upload_auto_adapts_k(monkeypatch):
    """TPU_COOC_UPLOAD_CHUNK_KB picks the smallest pow2 K that brings
    each piece under the byte target (window sizes are data-dependent,
    so fixed K leaves big windows above the transfer cliff); explicit
    TPU_COOC_UPLOAD_CHUNKS wins when both are set."""
    from tpu_cooccurrence.ops.device_scorer import split_upload_auto

    monkeypatch.delenv("TPU_COOC_UPLOAD_CHUNKS", raising=False)
    monkeypatch.setenv("TPU_COOC_UPLOAD_CHUNK_KB", "256")
    mb1 = np.zeros((2, 131072), dtype=np.int32)       # 1 MiB
    parts = split_upload_auto(mb1)
    assert len(parts) == 4                             # 4 x 256 KiB
    assert all(p.nbytes == 256 * 1024 for p in parts)
    mb4 = np.zeros((2, 524288), dtype=np.int32)        # 4 MiB -> 16 pieces
    assert len(split_upload_auto(mb4)) == 16
    small = np.zeros((2, 4096), dtype=np.int32)        # 32 KiB: monolithic
    assert split_upload_auto(small) is None
    # Chunk floor still applies: never below 1024 columns per piece.
    assert all(p.shape[1] >= 1024 for p in split_upload_auto(mb4))
    # Explicit K overrides the byte target.
    monkeypatch.setenv("TPU_COOC_UPLOAD_CHUNKS", "2")
    assert len(split_upload_auto(mb1)) == 2
    # A SET K=1 pins the MONOLITHIC arm even against an ambient
    # CHUNK_KB — the A/B's baseline must not silently chunk.
    monkeypatch.setenv("TPU_COOC_UPLOAD_CHUNKS", "1")
    monkeypatch.setenv("TPU_COOC_UPLOAD_CHUNK_KB", "256")
    assert split_upload_auto(mb1) is None
    # Both off: monolithic.
    monkeypatch.delenv("TPU_COOC_UPLOAD_CHUNKS")
    monkeypatch.setenv("TPU_COOC_UPLOAD_CHUNK_KB", "0")
    assert split_upload_auto(mb1) is None


def test_sparse_adaptive_chunked_matches(monkeypatch):
    """End-to-end parity under the adaptive byte-target policy."""
    users, items, ts = random_stream(13, n=1500, n_items=90)
    kw = dict(window_size=15, seed=21, item_cut=6, user_cut=4,
              backend=Backend.SPARSE, development_mode=True)
    a = run_production(Config(**kw), users, items, ts)
    monkeypatch.setenv("TPU_COOC_UPLOAD_CHUNK_KB", "16")  # tiny: forces K
    b = run_production(Config(**kw), users, items, ts)
    assert_latest_close(a.latest, b.latest)
    assert a.counters.as_dict() == b.counters.as_dict()


def test_sparse_deferred_matches_pipelined():
    """defer_results keeps results in the device table and fetches once:
    final state must equal the per-window pipelined mode's, and no
    per-window results may be emitted before the flush."""
    from tpu_cooccurrence.job import CooccurrenceJob

    kw = dict(window_size=10, seed=0xA1, item_cut=5, user_cut=4,
              development_mode=True)
    users, items, ts = random_stream(41, n=1200)

    def run(defer):
        cfg = Config(**kw, backend=Backend.SPARSE)
        scorer = SparseDeviceScorer(cfg.top_k, development_mode=True,
                                    capacity=64, items_capacity=8,
                                    compact_min_heap=256,
                                    defer_results=defer)
        job = CooccurrenceJob(cfg, scorer=scorer)
        scorer.counters = job.counters
        mid_stream_emissions = []
        job.on_update = lambda batch: mid_stream_emissions.append(len(batch))
        job.add_batch(users, items, ts)
        mid = list(mid_stream_emissions)
        job.finish()
        return job, mid

    piped, mid_p = run(False)
    deferred, mid_d = run(True)
    assert sum(mid_p) > 0          # pipelined mode streams mid-run
    assert mid_d == []             # deferred mode holds everything on device
    assert_latest_close(piped.latest, deferred.latest)
    # Structural growth paths ran under deferral too (table re-allocation).
    assert deferred.scorer.items_cap > 8


def test_sparse_deferred_flush_idempotent_and_checkpoint(tmp_path):
    """Periodic checkpoints flush the deferred table (idempotently); a
    restore repopulates results from the saved LatestResults and the
    post-restore windows, matching an uninterrupted run."""
    from tpu_cooccurrence.job import CooccurrenceJob

    kw = dict(window_size=10, seed=7, item_cut=5, user_cut=3,
              backend=Backend.SPARSE, checkpoint_dir=str(tmp_path / "ck"),
              development_mode=True)
    users, items, ts = random_stream(53, n=500)
    half = 230

    ref = CooccurrenceJob(Config(**kw))
    assert ref.scorer.defer_results   # job default: no --emit-updates
    ref.add_batch(users, items, ts)
    ref.finish()

    a = CooccurrenceJob(Config(**kw))
    a.add_batch(users[:half], items[:half], ts[:half])
    f1 = a.scorer.flush()
    assert len(f1) > 0       # first flush drains everything scored so far
    a._absorb(f1)            # flushed rows belong to the caller (the job
    # absorbs every flush; dropping one would lose results)
    f2 = a.scorer.flush()
    assert len(f2) == 0      # incremental: nothing new since -> no refetch
    a.checkpoint()
    b = CooccurrenceJob(Config(**kw))
    b.restore()
    b.add_batch(users[half:], items[half:], ts[half:])
    b.finish()
    assert_latest_close(ref.latest, b.latest, rtol=1e-6, atol=1e-6)


def test_sparse_fixed_shapes_matches_variable():
    """Fixed-shape scoring (constant per-bucket rectangles, TPU default)
    produces identical results to the variable pow-4 ladder."""
    from tpu_cooccurrence.job import CooccurrenceJob

    kw = dict(window_size=10, seed=0xF5, item_cut=5, user_cut=4,
              development_mode=True)
    users, items, ts = random_stream(59, n=1200)

    def run(fixed):
        cfg = Config(**kw, backend=Backend.SPARSE)
        scorer = SparseDeviceScorer(cfg.top_k, development_mode=True,
                                    capacity=64, items_capacity=8,
                                    compact_min_heap=256,
                                    defer_results=True, fixed_shapes=fixed)
        if fixed:
            # Small fixed rectangles so the CPU test stays quick; the
            # shape-constancy property is what is under test.
            scorer.FIXED_BUDGET = 1 << 12
            scorer.FIXED_ROW_CAP = 64
        job = CooccurrenceJob(cfg, scorer=scorer)
        scorer.counters = job.counters
        job.add_batch(users, items, ts)
        job.finish()
        return job

    var = run(False)
    fix = run(True)
    assert_latest_close(var.latest, fix.latest, rtol=1e-6, atol=1e-6)
    for name in (OBSERVED_COOCCURRENCES, ROW_SUM_PROCESS_WINDOW,
                 RESCORED_ITEMS):
        assert var.counters.get(name) == fix.counters.get(name), name


def test_sparse_fixed_shapes_dispatch_signature_constant():
    """Fixed mode scores a whole window in ONE dispatch whose static plan
    gives each bucket R a single constant S — the whole point (a handful
    of programs, one scoring dispatch per window)."""
    import tpu_cooccurrence.state.sparse_scorer as sp
    from tpu_cooccurrence.job import CooccurrenceJob

    plans = []
    calls = {"window": 0, "per_bucket": 0}
    orig_window = sp._score_window_into_table
    orig_bucket = sp._score_into_table

    def spy_window(tbl, cnt, dst, row_sums, meta_all, observed, *,
                   top_k, plan, interpret=False):
        calls["window"] += 1
        plans.append(plan)
        return orig_window(tbl, cnt, dst, row_sums, meta_all, observed,
                           top_k=top_k, plan=plan, interpret=interpret)

    def spy_bucket(*a, **k):
        calls["per_bucket"] += 1
        return orig_bucket(*a, **k)

    cfg = Config(window_size=10, seed=0xF6, item_cut=5, user_cut=4,
                 backend=Backend.SPARSE, development_mode=True)
    users, items, ts = random_stream(61, n=1500)
    scorer = SparseDeviceScorer(cfg.top_k, development_mode=True,
                                defer_results=True, fixed_shapes=True)
    scorer.FIXED_BUDGET = 1 << 12
    scorer.FIXED_ROW_CAP = 64
    job = CooccurrenceJob(cfg, scorer=scorer)
    scorer.counters = job.counters
    sp._score_window_into_table = spy_window
    sp._score_into_table = spy_bucket
    try:
        job.add_batch(users, items, ts)
        job.finish()
    finally:
        sp._score_window_into_table = orig_window
        sp._score_into_table = orig_bucket
    assert calls["window"] > 0       # fixed mode used the fused dispatch
    assert calls["per_bucket"] == 0  # never the per-bucket path
    # S is a pure function of R across EVERY dispatch of the stream
    # (constant rectangles — the invariant that bounds program count).
    s_by_r = {}
    for plan in plans:
        for r, s, _o, _pl in plan:
            assert s_by_r.setdefault(r, s) == s, (r, s, s_by_r)
    # The monotone high-water plan only ever grows: each plan's
    # (R -> chunk count) multiset extends its predecessor's.
    seen = {}
    for plan in plans:
        counts = {}
        for r, _s, _o, _pl in plan:
            counts[r] = counts.get(r, 0) + 1
        for r, n in seen.items():
            assert counts.get(r, 0) >= n, (seen, counts)
        seen = counts
    # Program count bounded by the final plan count, not window count.
    assert len(set(plans)) <= sum(seen.values())


def test_sparse_fixed_shapes_chunk_overflow_plan_persists():
    """A bucket overflowing its per-dispatch row cap adds chunk-rank
    entries to the plan; later smaller windows RETAIN them (all-padding)
    so the fused program never retraces."""
    import tpu_cooccurrence.state.sparse_scorer as sp
    from tpu_cooccurrence.job import CooccurrenceJob

    plans = []
    orig = sp._score_window_into_table

    def spy(*a, **k):
        plans.append(k["plan"])
        return orig(*a, **k)

    cfg = Config(window_size=10, seed=2, skip_cuts=True,
                 development_mode=True)
    sc = sp.SparseDeviceScorer(cfg.top_k, development_mode=True,
                               defer_results=True, fixed_shapes=True)
    sc.FIXED_BUDGET = 1 << 10
    sc.FIXED_ROW_CAP = 16   # force chunk overflow on the busy window
    job = CooccurrenceJob(cfg, scorer=sc)
    sc.counters = job.counters
    u1 = np.zeros(40, np.int64)
    i1 = np.arange(40, dtype=np.int64)
    u2 = np.zeros(5, np.int64)
    i2 = np.arange(5, dtype=np.int64)
    sp._score_window_into_table = spy
    try:
        job.add_batch(np.concatenate([u1, u2]),
                      np.concatenate([i1, i2]),
                      np.concatenate([np.full(40, 5, np.int64),
                                      np.full(5, 15, np.int64)]))
        job.finish()
    finally:
        sp._score_window_into_table = orig
    assert len(plans) >= 2
    assert len(plans[0]) >= 3          # the busy window overflowed
    assert len(set(plans)) == 1        # one program for the whole stream


def test_hash_index_matches_sorted_index():
    """The native hash index and the sorted fallback must be plan-for-plan
    identical across appends, relocations, compactions, and rebuilds."""
    import pytest

    from tpu_cooccurrence.native import get_lib
    from tpu_cooccurrence.state.sparse_scorer import (HashSlabIndex,
                                                      SlabIndex)

    if get_lib() is None:
        pytest.skip("native library unavailable")
    rng = np.random.default_rng(0xF00D)
    a = SlabIndex(rows_capacity=8)
    b = HashSlabIndex(rows_capacity=8)
    for window in range(120):
        n = int(rng.integers(1, 120))
        rows = rng.integers(0, 50, n).astype(np.int64)
        dsts = rng.integers(0, 1 + int(rng.integers(1, 200)), n)
        d_key = np.unique((rows << 32) | dsts)
        pa = a.apply(d_key.copy())
        pb = b.apply(d_key.copy())
        np.testing.assert_array_equal(pa.new_sel, pb.new_sel)
        np.testing.assert_array_equal(pa.slots, pb.slots)
        assert a.heap_end == b.heap_end
        if pa.mv is not None or pb.mv is not None:
            np.testing.assert_array_equal(pa.mv, pb.mv)
        if a.needs_compaction(256):
            np.testing.assert_array_equal(a.compact(), b.compact())
    ka, sa = a.keys_and_slots()
    kb, sb = b.keys_and_slots()
    np.testing.assert_array_equal(ka, kb)
    np.testing.assert_array_equal(sa, sb)
    # Restore path: both rebuild to the same layout and keep agreeing.
    np.testing.assert_array_equal(a.rebuild_from_keys(ka.copy()),
                                  b.rebuild_from_keys(ka.copy()))
    pa = a.apply(ka[:7].copy())
    pb = b.apply(ka[:7].copy())
    np.testing.assert_array_equal(pa.slots, pb.slots)
