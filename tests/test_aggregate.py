"""Shared per-window COO aggregation (ops/aggregate.py)."""

import numpy as np
import pytest

from tpu_cooccurrence.ops.aggregate import aggregate_window_coo, distinct_sorted


def test_aggregate_folds_duplicates_exactly():
    rng = np.random.default_rng(11)
    n = 50_000
    src = rng.integers(0, 300, n).astype(np.int64)
    dst = rng.integers(0, 300, n).astype(np.int64)
    delta = rng.integers(-1, 3, n).astype(np.int64)

    a_src, a_dst, a_delta = aggregate_window_coo(src, dst, delta)

    dense = np.zeros((300, 300), dtype=np.int64)
    np.add.at(dense, (src, dst), delta)
    got = np.zeros_like(dense)
    np.add.at(got, (a_src, a_dst), a_delta.astype(np.int64))
    np.testing.assert_array_equal(got, dense)

    # One entry per distinct cell, sorted by (src, dst).
    key = (a_src.astype(np.int64) << 32) | a_dst.astype(np.int64)
    assert (np.diff(key) > 0).all()
    # Net-zero cells are kept (the reference also rescores their rows).
    assert (a_delta == 0).any()


def test_aggregate_empty():
    e = np.zeros(0, dtype=np.int64)
    a_src, a_dst, a_delta = aggregate_window_coo(e, e, e)
    assert len(a_src) == len(a_dst) == len(a_delta) == 0


def test_distinct_sorted():
    assert distinct_sorted(np.array([], dtype=np.int32)).size == 0
    np.testing.assert_array_equal(
        distinct_sorted(np.array([0, 0, 2, 5, 5, 5, 9], dtype=np.int32)),
        [0, 2, 5, 9])
    np.testing.assert_array_equal(
        distinct_sorted(np.array([3], dtype=np.int32)), [3])


def test_native_fold_matches_numpy_unique():
    """The native sort-and-fold must be bit-identical to the np.unique
    path (same sorted keys, same int64 sums) on adversarial inputs:
    heavy duplication, cancellations to zero, singleton tails."""
    from tpu_cooccurrence.native import coo_aggregate

    rng = np.random.default_rng(11)
    for n in (1, 2, 7, 1000, 50_000):
        src = rng.integers(0, 50, n).astype(np.int64)
        dst = rng.integers(0, 40, n).astype(np.int64)
        delta = rng.choice(np.array([-1, 1], dtype=np.int64), n)
        key = (src << 32) | dst
        uniq_ref, inverse = np.unique(key, return_inverse=True)
        agg_ref = np.bincount(inverse, weights=delta,
                              minlength=len(uniq_ref)).astype(np.int64)
        folded = coo_aggregate(key, delta)
        if folded is None:  # no native lib on this box: numpy path only
            return
        uniq, agg = folded
        np.testing.assert_array_equal(uniq, uniq_ref)
        np.testing.assert_array_equal(agg, agg_ref)
        # Inputs must be untouched (callers reuse them).
        assert (key == ((src << 32) | dst)).all()
    s2, d2, a2, k2 = aggregate_window_coo(src, dst, delta,
                                          return_key=True)
    np.testing.assert_array_equal(k2, uniq_ref)
    np.testing.assert_array_equal(a2, agg_ref)
    assert s2.dtype == np.int32 and d2.dtype == np.int32


def test_integrated_native_branch_matches(monkeypatch):
    """Drive aggregate_window_coo's NATIVE branch (normally gated at
    2M deltas) by lowering the threshold: results must match the numpy
    branch exactly, and caller arrays must survive (only the internal
    packed-key local is clobbered)."""
    from tpu_cooccurrence.native import coo_aggregate, get_lib
    from tpu_cooccurrence.ops import aggregate as agg_mod

    if get_lib() is None:
        return  # numpy-only box: nothing to compare
    rng = np.random.default_rng(5)
    n = 30_000
    src = rng.integers(0, 300, n).astype(np.int64)
    dst = rng.integers(0, 200, n).astype(np.int64)
    delta = rng.choice(np.array([-1, 1], dtype=np.int64), n)
    ref = aggregate_window_coo(src, dst, delta, return_key=True)
    monkeypatch.setattr(agg_mod, "NATIVE_FOLD_MIN", 1)
    src_c, dst_c, delta_c = src.copy(), dst.copy(), delta.copy()
    got = agg_mod.aggregate_window_coo(src, dst, delta, return_key=True)
    for r, g in zip(ref, got):
        np.testing.assert_array_equal(r, g)
    np.testing.assert_array_equal(src, src_c)
    np.testing.assert_array_equal(dst, dst_c)
    np.testing.assert_array_equal(delta, delta_c)


def test_native_fold_length_mismatch_raises():
    from tpu_cooccurrence.native import coo_aggregate, get_lib

    if get_lib() is None:
        return
    import pytest

    with pytest.raises(ValueError, match="delta length"):
        coo_aggregate(np.zeros(4, dtype=np.int64),
                      np.zeros(3, dtype=np.int64))

def test_native_fold_rejects_non_integer_delta():
    """The native int64 fold would silently truncate fractional deltas
    where the float64 NumPy fallback sums them exactly — non-integer
    input must raise instead of folding differently by code path."""
    from tpu_cooccurrence.native import coo_aggregate, get_lib

    if get_lib() is None:
        pytest.skip("native fold unavailable; dtype guard unexercised")

    with pytest.raises(TypeError, match="integer"):
        coo_aggregate(np.zeros(3, dtype=np.int64),
                      np.asarray([0.5, 1.0, 2.0]))


def test_return_key_does_not_pin_full_buffer(monkeypatch):
    """return_key=True hands back an owning copy, not a prefix view of the
    (potentially >= 4M-entry) packed-key work buffer.

    The hazard lives in the NATIVE branch (the fold returns a prefix view
    of its full sort buffer), so the threshold is lowered to force that
    routing; the numpy fallback's np.unique output owns its memory either
    way."""
    from tpu_cooccurrence.native import get_lib
    from tpu_cooccurrence.ops import aggregate

    if get_lib() is None:
        pytest.skip("native fold unavailable; fallback output always owns")
    monkeypatch.setattr(aggregate, "NATIVE_FOLD_MIN", 1)
    src = np.asarray([3, 1, 1, 3], dtype=np.int32)
    dst = np.asarray([0, 2, 2, 0], dtype=np.int32)
    delta = np.asarray([1, 1, 1, 1], dtype=np.int64)
    _, _, agg, key = aggregate_window_coo(src, dst, delta, return_key=True)
    assert key.base is None, "d_key must own its memory"
    assert agg.base is None, "folded deltas must own their memory"
    np.testing.assert_array_equal(
        key, np.asarray([(1 << 32) | 2, (3 << 32) | 0], dtype=np.int64))
    np.testing.assert_array_equal(agg, np.asarray([2, 2], dtype=np.int64))


def test_aggregated_pairs_fold_matches_direct():
    from tpu_cooccurrence.ops.aggregate import AggregatedPairs

    src = np.asarray([5, 2, 5, 2, 7], dtype=np.int32)
    dst = np.asarray([1, 3, 1, 3, 0], dtype=np.int32)
    delta = np.asarray([1, -1, 2, 4, 1], dtype=np.int64)
    agg = AggregatedPairs.fold(src, dst, delta)
    s, d, v, k = aggregate_window_coo(src, dst, delta, return_key=True)
    np.testing.assert_array_equal(agg.src, s)
    np.testing.assert_array_equal(agg.dst, d)
    np.testing.assert_array_equal(agg.delta, v)
    np.testing.assert_array_equal(agg.key, k)
    assert len(agg) == len(s)
