"""Shared per-window COO aggregation (ops/aggregate.py)."""

import numpy as np

from tpu_cooccurrence.ops.aggregate import aggregate_window_coo, distinct_sorted


def test_aggregate_folds_duplicates_exactly():
    rng = np.random.default_rng(11)
    n = 50_000
    src = rng.integers(0, 300, n).astype(np.int64)
    dst = rng.integers(0, 300, n).astype(np.int64)
    delta = rng.integers(-1, 3, n).astype(np.int64)

    a_src, a_dst, a_delta = aggregate_window_coo(src, dst, delta)

    dense = np.zeros((300, 300), dtype=np.int64)
    np.add.at(dense, (src, dst), delta)
    got = np.zeros_like(dense)
    np.add.at(got, (a_src, a_dst), a_delta.astype(np.int64))
    np.testing.assert_array_equal(got, dense)

    # One entry per distinct cell, sorted by (src, dst).
    key = (a_src.astype(np.int64) << 32) | a_dst.astype(np.int64)
    assert (np.diff(key) > 0).all()
    # Net-zero cells are kept (the reference also rescores their rows).
    assert (a_delta == 0).any()


def test_aggregate_empty():
    e = np.zeros(0, dtype=np.int64)
    a_src, a_dst, a_delta = aggregate_window_coo(e, e, e)
    assert len(a_src) == len(a_dst) == len(a_delta) == 0


def test_distinct_sorted():
    assert distinct_sorted(np.array([], dtype=np.int32)).size == 0
    np.testing.assert_array_equal(
        distinct_sorted(np.array([0, 0, 2, 5, 5, 5, 9], dtype=np.int32)),
        [0, 2, 5, 9])
    np.testing.assert_array_equal(
        distinct_sorted(np.array([3], dtype=np.int32)), [3])
