"""Sliding-window (windowed-basket) mode tests.

A naive per-window recount oracle validates the vectorized basket pair
expansion; overlap semantics are checked against hand-computed window
contents."""

import numpy as np
import pytest

from tpu_cooccurrence.config import Backend, Config
from tpu_cooccurrence.job import CooccurrenceJob
from tpu_cooccurrence.sampling.sliding import SlidingBasketSampler


def naive_basket_pairs(users, items):
    """All ordered pairs of distinct basket positions, per user."""
    agg = {}
    baskets = {}
    for u, i in zip(users, items):
        baskets.setdefault(u, []).append(i)
    for basket in baskets.values():
        for a in range(len(basket)):
            for b in range(len(basket)):
                if a != b:
                    key = (basket[a], basket[b])
                    agg[key] = agg.get(key, 0) + 1
    return agg


def aggregate(pairs):
    agg = {}
    for s, d, v in zip(pairs.src.tolist(), pairs.dst.tolist(),
                       pairs.delta.tolist()):
        agg[(s, d)] = agg.get((s, d), 0) + v
    return agg


def test_basket_expansion_matches_naive():
    rng = np.random.default_rng(5)
    sampler = SlidingBasketSampler(500, 500, skip_cuts=True)
    for _ in range(20):
        n = int(rng.integers(1, 60))
        users = rng.integers(0, 6, n).astype(np.int64)
        items = rng.integers(0, 10, n).astype(np.int64)
        pairs = sampler.fire(users, items)
        assert aggregate(pairs) == naive_basket_pairs(
            users.tolist(), items.tolist())


def test_basket_caps():
    sampler = SlidingBasketSampler(item_cut=2, user_cut=3, skip_cuts=False)
    # user 0 has 5 interactions; cap keeps first 3. item 7 appears 3x
    # globally; cap keeps first 2 occurrences.
    users = np.array([0, 0, 0, 0, 0, 1], dtype=np.int64)
    items = np.array([7, 8, 7, 9, 9, 7], dtype=np.int64)
    pairs = sampler.fire(users, items)
    # Kept: user0 ranks 0,1,2 of [7,8,7,9,9] intersect item caps:
    # item7 ranks: events 0 (rank0), 2 (rank1), 5 (rank2->cut).
    # kept mask: e0 (u-rank0,i-rank0) yes; e1 (8) yes; e2 (7 rank1, u-rank2)
    # yes; e3 (9, u-rank3) no; e4 no; e5 (7 rank2) no.
    assert aggregate(pairs) == naive_basket_pairs([0, 0, 0], [7, 8, 7])


def test_sliding_pipeline_overlap_hand_checked():
    cfg = Config(window_size=10, window_slide=5, skip_cuts=True, seed=1,
                 backend=Backend.ORACLE)
    job = CooccurrenceJob(cfg)
    # Events: u1 at ts=3 (item A=100), ts=7 (item B=200).
    # Windows [-5,5): {A}; [0,10): {A,B}; [5,15): {B}.
    # Only [0,10) yields pairs: (A,B) and (B,A) once each.
    users = np.array([1, 1], dtype=np.int64)
    items = np.array([100, 200], dtype=np.int64)
    ts = np.array([3, 7], dtype=np.int64)
    job.add_batch(users, items, ts)
    job.finish()
    assert job.windows_fired == 3
    assert set(job.latest) == {100, 200}
    # C[100][200] == 1: scored once in window [0,10).
    (other, score), = job.latest[100]
    assert other == 200
    assert score > 0


def test_sliding_overlap_double_counts_pairs():
    # Two items in the same slide bucket co-occur in BOTH overlapping
    # windows -> pair count 2.
    cfg = Config(window_size=10, window_slide=5, skip_cuts=True, seed=1,
                 backend=Backend.ORACLE)
    job = CooccurrenceJob(cfg)
    job.add_batch(np.array([1, 1]), np.array([100, 200]),
                  np.array([6, 7], dtype=np.int64))
    job.finish()
    scorer = job.scorer
    # dense ids 0,1
    assert scorer.item_rows[0] == {1: 2}
    assert scorer.observed == 4


def test_sliding_device_matches_oracle_backend():
    rng = np.random.default_rng(11)
    n = 300
    users = rng.integers(0, 10, n).astype(np.int64)
    items = rng.integers(0, 20, n).astype(np.int64)
    ts = np.cumsum(rng.integers(0, 2, n)).astype(np.int64)
    kw = dict(window_size=20, window_slide=5, skip_cuts=False,
              item_cut=6, user_cut=5, seed=3)
    a = CooccurrenceJob(Config(**kw, backend=Backend.ORACLE))
    a.add_batch(users, items, ts)
    a.finish()
    b = CooccurrenceJob(Config(**kw, backend=Backend.DEVICE, num_items=32))
    b.add_batch(users, items, ts)
    b.finish()
    assert set(a.latest) == set(b.latest)
    for item in a.latest:
        o = np.array([s for _, s in a.latest[item]])
        d = np.array([s for _, s in b.latest[item]])
        assert len(o) == len(d)
        np.testing.assert_allclose(d, o, rtol=1e-4, atol=1e-3)


def test_sliding_slide_must_divide():
    with pytest.raises(ValueError):
        CooccurrenceJob(Config(window_size=10, window_slide=3, seed=1))
