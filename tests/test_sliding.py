"""Sliding-window (windowed-basket) mode tests.

A naive per-window recount oracle validates the vectorized basket pair
expansion; overlap semantics are checked against hand-computed window
contents."""

import numpy as np
import pytest

from tpu_cooccurrence.config import Backend, Config
from tpu_cooccurrence.job import CooccurrenceJob
from tpu_cooccurrence.metrics import (
    OBSERVED_COOCCURRENCES,
    RESCORED_ITEMS,
    ROW_SUM_PROCESS_WINDOW,
)
from tpu_cooccurrence.sampling.sliding import SlidingBasketSampler


def naive_basket_pairs(users, items):
    """All ordered pairs of distinct basket positions, per user."""
    agg = {}
    baskets = {}
    for u, i in zip(users, items):
        baskets.setdefault(u, []).append(i)
    for basket in baskets.values():
        for a in range(len(basket)):
            for b in range(len(basket)):
                if a != b:
                    key = (basket[a], basket[b])
                    agg[key] = agg.get(key, 0) + 1
    return agg


def aggregate(pairs):
    agg = {}
    for s, d, v in zip(pairs.src.tolist(), pairs.dst.tolist(),
                       pairs.delta.tolist()):
        agg[(s, d)] = agg.get((s, d), 0) + v
    return agg


def test_basket_expansion_matches_naive():
    rng = np.random.default_rng(5)
    sampler = SlidingBasketSampler(500, 500, skip_cuts=True)
    for _ in range(20):
        n = int(rng.integers(1, 60))
        users = rng.integers(0, 6, n).astype(np.int64)
        items = rng.integers(0, 10, n).astype(np.int64)
        pairs = sampler.fire(users, items)
        assert aggregate(pairs) == naive_basket_pairs(
            users.tolist(), items.tolist())


def test_basket_caps():
    sampler = SlidingBasketSampler(item_cut=2, user_cut=3, skip_cuts=False)
    # user 0 has 5 interactions; cap keeps first 3. item 7 appears 3x
    # globally; cap keeps first 2 occurrences.
    users = np.array([0, 0, 0, 0, 0, 1], dtype=np.int64)
    items = np.array([7, 8, 7, 9, 9, 7], dtype=np.int64)
    pairs = sampler.fire(users, items)
    # Kept: user0 ranks 0,1,2 of [7,8,7,9,9] intersect item caps:
    # item7 ranks: events 0 (rank0), 2 (rank1), 5 (rank2->cut).
    # kept mask: e0 (u-rank0,i-rank0) yes; e1 (8) yes; e2 (7 rank1, u-rank2)
    # yes; e3 (9, u-rank3) no; e4 no; e5 (7 rank2) no.
    assert aggregate(pairs) == naive_basket_pairs([0, 0, 0], [7, 8, 7])


def test_sliding_pipeline_overlap_hand_checked():
    cfg = Config(window_size=10, window_slide=5, skip_cuts=True, seed=1,
                 backend=Backend.ORACLE)
    job = CooccurrenceJob(cfg)
    # Events: u1 at ts=3 (item A=100), ts=7 (item B=200).
    # Windows [-5,5): {A}; [0,10): {A,B}; [5,15): {B}.
    # Only [0,10) yields pairs: (A,B) and (B,A) once each.
    users = np.array([1, 1], dtype=np.int64)
    items = np.array([100, 200], dtype=np.int64)
    ts = np.array([3, 7], dtype=np.int64)
    job.add_batch(users, items, ts)
    job.finish()
    assert job.windows_fired == 3
    assert set(job.latest) == {100, 200}
    # C[100][200] == 1: scored once in window [0,10).
    (other, score), = job.latest[100]
    assert other == 200
    assert score > 0


def test_sliding_overlap_double_counts_pairs():
    # Two items in the same slide bucket co-occur in BOTH overlapping
    # windows -> pair count 2.
    cfg = Config(window_size=10, window_slide=5, skip_cuts=True, seed=1,
                 backend=Backend.ORACLE)
    job = CooccurrenceJob(cfg)
    job.add_batch(np.array([1, 1]), np.array([100, 200]),
                  np.array([6, 7], dtype=np.int64))
    job.finish()
    scorer = job.scorer
    # dense ids 0,1
    assert scorer.item_rows[0] == {1: 2}
    assert scorer.observed == 4


def test_sliding_device_matches_oracle_backend():
    rng = np.random.default_rng(11)
    n = 300
    users = rng.integers(0, 10, n).astype(np.int64)
    items = rng.integers(0, 20, n).astype(np.int64)
    ts = np.cumsum(rng.integers(0, 2, n)).astype(np.int64)
    kw = dict(window_size=20, window_slide=5, skip_cuts=False,
              item_cut=6, user_cut=5, seed=3)
    a = CooccurrenceJob(Config(**kw, backend=Backend.ORACLE))
    a.add_batch(users, items, ts)
    a.finish()
    b = CooccurrenceJob(Config(**kw, backend=Backend.DEVICE, num_items=32))
    b.add_batch(users, items, ts)
    b.finish()
    assert set(a.latest) == set(b.latest)
    for item in a.latest:
        o = np.array([s for _, s in a.latest[item]])
        d = np.array([s for _, s in b.latest[item]])
        assert len(o) == len(d)
        np.testing.assert_allclose(d, o, rtol=1e-4, atol=1e-3)


def _run_sliding_oracle(cfg, users, items, ts):
    from tpu_cooccurrence.oracle.sliding import SlidingOracleJob

    oracle = SlidingOracleJob(cfg)
    for u, i, t in zip(users.tolist(), items.tolist(), ts.tolist()):
        oracle.process(u, i, t)
    oracle.finish()
    return oracle


@pytest.mark.parametrize("overrides", [
    dict(skip_cuts=True),
    dict(item_cut=6, user_cut=5),
    dict(item_cut=3, user_cut=2, window_slide=10),
    dict(item_cut=500, user_cut=500, window_size=40, window_slide=8),
])
def test_sliding_end_to_end_matches_record_at_a_time_oracle(overrides):
    """The full production sliding path (vectorized engine + per-window
    caps + ragged basket expansion + scorer) against the naive
    record-at-a-time SlidingOracleJob, across caps and overlaps."""
    from test_pipeline import assert_latest_close, relabel_first_appearance

    kw = dict(window_size=20, window_slide=5, seed=9,
              development_mode=True)
    kw.update(overrides)
    rng = np.random.default_rng(sum(kw["window_size"] for _ in [0]) + 17)
    n = 700
    users = relabel_first_appearance(rng.integers(0, 9, n))
    items = relabel_first_appearance(rng.integers(0, 25, n))
    ts = np.cumsum(rng.integers(0, 3, n)).astype(np.int64)

    oracle = _run_sliding_oracle(Config(**kw, backend=Backend.ORACLE),
                                 users, items, ts)

    for backend, extra in [(Backend.ORACLE, {}),
                           (Backend.DEVICE, dict(num_items=32))]:
        job = CooccurrenceJob(Config(**kw, backend=backend, **extra))
        for lo in range(0, n, 93):  # batch boundaries must not matter
            job.add_batch(users[lo:lo + 93], items[lo:lo + 93],
                          ts[lo:lo + 93])
        job.finish()
        prod_latest = {item: job.latest[item] for item in job.latest}
        if backend == Backend.ORACLE:
            # Same f64 math end to end: exact equality expected.
            assert set(oracle.latest) == set(prod_latest)
            for item in oracle.latest:
                assert sorted(oracle.latest[item],
                              key=lambda e: (-e[1], e[0])) == \
                    sorted(prod_latest[item], key=lambda e: (-e[1], e[0])), \
                    f"row {item}"
        else:
            assert_latest_close(oracle.latest, prod_latest)
        for name in (OBSERVED_COOCCURRENCES, ROW_SUM_PROCESS_WINDOW,
                     RESCORED_ITEMS):
            assert oracle.counters.get(name) == job.counters.get(name), name


def test_sliding_slide_must_divide():
    with pytest.raises(ValueError):
        CooccurrenceJob(Config(window_size=10, window_slide=3, seed=1))


@pytest.mark.parametrize("skip_cuts", [False, True])
@pytest.mark.parametrize("f_max,k_max", [(500, 500), (3, 4), (1, 1)])
def test_native_sliding_matches_numpy(skip_cuts, f_max, k_max):
    """The C++ expansion is byte-identical to the NumPy path (same pair
    ORDER, not just the same multiset)."""
    from tpu_cooccurrence import native

    if native.get_lib() is None:
        pytest.skip("native library unavailable")
    rng = np.random.default_rng(0xBEEF)
    for trial in range(6):
        n = int(rng.integers(1, 400))
        users = rng.integers(0, 12, n).astype(np.int64)
        items = rng.integers(0, 30, n).astype(np.int64)
        s_native = SlidingBasketSampler(f_max, k_max, skip_cuts)
        s_numpy = SlidingBasketSampler(f_max, k_max, skip_cuts)
        got = s_native.fire(users, items)
        want = s_numpy._fire_numpy(users.copy(), items.copy())
        np.testing.assert_array_equal(got.src, want.src)
        np.testing.assert_array_equal(got.dst, want.dst)
        np.testing.assert_array_equal(got.delta, want.delta)


def test_native_sliding_scratch_reuse_across_windows():
    """Persistent scratch is re-zeroed correctly between fires."""
    from tpu_cooccurrence import native

    if native.get_lib() is None:
        pytest.skip("native library unavailable")
    rng = np.random.default_rng(7)
    s_native = SlidingBasketSampler(5, 5, False)
    s_numpy = SlidingBasketSampler(5, 5, False)
    for trial in range(8):
        n = int(rng.integers(1, 300))
        # Growing id ranges exercise scratch growth + prefix re-zeroing.
        hi = 10 * (trial + 1)
        users = rng.integers(0, hi, n).astype(np.int64)
        items = rng.integers(0, 3 * hi, n).astype(np.int64)
        got = s_native.fire(users, items)
        want = s_numpy._fire_numpy(users.copy(), items.copy())
        np.testing.assert_array_equal(got.src, want.src)
        np.testing.assert_array_equal(got.dst, want.dst)


def test_native_cut_mask_matches_grouped_rank():
    from tpu_cooccurrence import native
    from tpu_cooccurrence.sampling.item_cut import grouped_rank

    if native.get_lib() is None:
        pytest.skip("native library unavailable")
    rng = np.random.default_rng(0xCAFE)
    scratch = native.SlidingScratch()
    for f_max, k_max in [(500, 500), (3, 4), (1, 1)]:
        for _ in range(4):
            n = int(rng.integers(1, 500))
            users = rng.integers(0, 20, n).astype(np.int64)
            items = rng.integers(0, 60, n).astype(np.int64)
            want = ((grouped_rank(items) < f_max)
                    & (grouped_rank(users) < k_max))
            got = native.sliding_cut_mask(users, items, f_max, k_max,
                                          scratch)
            np.testing.assert_array_equal(got, want)
