"""Partitioned-ingest chaos capstone (ISSUE 18): the real CLI in gang
mode on a partitioned log, killed mid-run, rescaled on recovery.

The claim under test is the tentpole's end-to-end exactly-once story:
per-partition offsets commit atomically with the state, so a gang that
is **kill -9'd mid-window at N workers and resumed at M workers**
(autoscale target pending across the crash, topology-aware restore
vote, ``merge_ingest_offsets`` on the wire) produces **bit-identical
stdout** to an unkilled fixed-topology run — zero events lost, zero
double-counted.

The stream is split CONTIGUOUSLY across three ``part-*`` files, each
smaller than one round-robin turn (TURN_RECORDS=256), so the
partitioned drain order equals the single-file order and the files/
partitioned equivalence test below holds the two sources to the same
output. The comparator follows test_autoscale_chaos: a fixed 2-worker
run crash-recovered at the elastic run's drain windows (restore
canonicalizes slab order, so the reference must restore at the same
boundaries — the seam-crash restore lands on the drain-committed
generation, i.e. exactly those boundaries).

The ledger: the journal's per-window ``events`` counts are raw windowed
line counts, so with window seqs exactly ``1..N`` each-once, their sum
equals the stream length — 520 — iff no event was lost or
double-counted across the kill and both rescale seams. The final
committed checkpoint's ``ingest_offsets`` must match the last journaled
window's — the wire and the state commit the same boundary.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ENV = dict(os.environ, JAX_PLATFORMS="cpu",
           XLA_FLAGS="--xla_force_host_platform_device_count=1",
           PALLAS_AXON_POOL_IPS="")

N_EVENTS = 520


def _event(i):
    return f"{i % 13},{i % 17},{i * 10}\n"


@pytest.fixture(scope="module")
def stream_csv(tmp_path_factory):
    path = tmp_path_factory.mktemp("pchaos") / "in.csv"
    with open(path, "w") as fh:
        for i in range(N_EVENTS):
            fh.write(_event(i))
    return str(path)


@pytest.fixture(scope="module")
def plog(tmp_path_factory):
    """The same 520 events split contiguously over 3 partitions, each
    under TURN_RECORDS so one turn drains it whole and the interleaved
    order equals the single-file order (timestamps stay ascending)."""
    root = tmp_path_factory.mktemp("pchaos-log") / "plog"
    root.mkdir()
    bounds = [(0, 174), (174, 348), (348, N_EVENTS)]
    for p, (lo, hi) in enumerate(bounds):
        with open(root / f"part-{p:03d}", "w") as fh:
            for i in range(lo, hi):
                fh.write(_event(i))
    return str(root)


_PARTITIONED = ["--source-format", "partitioned",
                "--ingest-partitions", "3"]


def _args(inp, ck_dir, extra):
    return [sys.executable, "-m", "tpu_cooccurrence.cli",
            "-i", inp, "-ws", "250", "-ic", "8", "-uc", "5",
            "-s", "0xC0FFEE", "--backend", "sparse",
            "--num-shards", "2",
            "--checkpoint-dir", ck_dir,
            "--checkpoint-every-windows", "1",
            "--checkpoint-retain", "100",
            "--gang-workers", "2", "--gang-heartbeat-s", "1",
            "--collective-timeout-s", "60",
            "--restart-delay-ms", "0"] + _PARTITIONED + extra

_LOAD = ["--inject-fault", "window_fire@0:3:delay_ms:2500",
         "--inject-fault", "window_fire@0:4:delay_ms:2500",
         "--inject-fault", "window_fire@0:5:delay_ms:2500"]

_AUTOSCALE = ["--degrade", "--degrade-window-wall-s", "2.0",
              "--degrade-trip-windows", "3",
              "--autoscale", "on",
              "--autoscale-min-workers", "2",
              "--autoscale-max-workers", "4",
              "--autoscale-trip-windows", "2",
              "--autoscale-clear-windows", "3",
              "--autoscale-cooldown-windows", "2"]


def _run(inp, ck_dir, extra, timeout=420):
    return subprocess.run(_args(inp, ck_dir, extra),
                          capture_output=True, text=True, env=ENV,
                          cwd=REPO, timeout=timeout)


def _journal_records(jpath, pid):
    with open(f"{jpath}.p{pid}") as f:
        return [json.loads(line) for line in f if line.strip()]


def test_partitioned_stream_matches_files_stream(stream_csv, plog):
    """Single process, no gang: the partitioned source's interleave of
    the contiguous split reproduces the files source's stream exactly
    (the precondition every comparator below rests on)."""
    base = [sys.executable, "-m", "tpu_cooccurrence.cli",
            "-ws", "250", "-ic", "8", "-uc", "5", "-s", "0xC0FFEE",
            "--backend", "sparse"]
    a = subprocess.run(base + ["-i", stream_csv], capture_output=True,
                       text=True, env=ENV, cwd=REPO, timeout=300)
    b = subprocess.run(base + ["-i", plog] + _PARTITIONED,
                       capture_output=True, text=True, env=ENV,
                       cwd=REPO, timeout=300)
    assert a.returncode == 0, a.stderr[-3000:]
    assert b.returncode == 0, b.stderr[-3000:]
    assert a.stdout, "files run produced no output"
    assert a.stdout == b.stdout


def _fixed_topology_reference(plog, tmp_path, drain_windows,
                              last_window):
    """Bit-exact comparator: fixed 2-worker gang on the same partition
    set, crash-recovered at exactly the elastic run's drain windows
    (test_autoscale_chaos's comparator, on the partitioned source)."""
    replay = [w for w in drain_windows if w < last_window]
    ck = str(tmp_path / "ck-ref")
    extra = ["--restart-on-failure", str(len(replay))]
    for w in replay:
        # Built by concatenation, not an f-string: the fault-site text
        # scan must see the site name at the spec's head.
        extra += ["--inject-fault",
                  "window_fire@0:" + str(w + 1) + ":crash"]
    extra += ["--fault-state-dir", str(tmp_path / "faults-ref")]
    proc = _run(plog, ck, extra)
    assert proc.returncode == 0, proc.stderr[-3000:]
    assert proc.stdout, "reference run produced no output"
    assert proc.stderr.count("gang-restarting") == len(replay)
    return proc.stdout


def test_kill_midrun_resume_rescaled_exactly_once(tmp_path, plog):
    """THE capstone: a 2-worker gang on the partitioned log is killed
    inside the grow seam (``rescale_drain@1:crash`` — the drain
    checkpoint committed, worker 1 dies before its voluntary exit),
    relaunches at 4 workers via the pending autoscale target + restore
    vote, later decays back to 2 — and the stdout is bit-identical to
    the fixed-topology comparator, with the event ledger and the
    committed offsets proving zero loss / zero double-count."""
    ck = str(tmp_path / "ck")
    jpath = str(tmp_path / "journal.jsonl")
    proc = _run(plog, ck,
                _AUTOSCALE + _LOAD
                + ["--restart-on-failure", "2",
                   "--journal", jpath,
                   "--inject-fault", "rescale_drain@1:crash",
                   "--fault-state-dir", str(tmp_path / "faults")])
    assert proc.returncode == 0, proc.stderr[-3000:]
    # The kill was real (billed restart) and the recovery crossed the
    # topology: 2-writer generation restored onto the 4-worker gang.
    assert "gang-restarting" in proc.stderr
    assert "rescale restore: generation" in proc.stderr
    fired = sorted(os.listdir(tmp_path / "faults"))
    assert "fault3.p1.fired" in fired  # the seam kill, worker 1 only

    recs = _journal_records(jpath, 0)
    scale = [r for r in recs if "autoscale" in r]
    assert [(r["from"], r["to"]) for r in scale] == [(2, 4), (4, 2)]

    # Zero lost, zero duplicated windows across the kill + both seams.
    windows = [r for r in recs if "seq" in r]
    seqs = [r["seq"] for r in windows]
    assert sorted(seqs) == list(range(1, max(seqs) + 1))
    assert len(seqs) == len(set(seqs))

    # The event-count ledger: every one of the 520 stream events landed
    # in exactly one window record.
    assert sum(r["events"] for r in windows) == N_EVENTS

    # Per-window wire telemetry rode the journal (partitioned source).
    assert all("ingest_offsets" in r and "ingest_lag" in r
               for r in windows)

    # The reassignment seams were journaled (cooc-trace annotates them).
    events = [r["event"] for r in recs if "event" in r]
    assert "ingest/partition-reassign:2->4" in events
    assert "ingest/partition-reassign:4->2" in events

    # The wire and the state committed the same boundary: the final
    # generation's offset section equals the last journaled window's,
    # and it accounts for the entire stream.
    from tpu_cooccurrence.state import checkpoint as ckpt

    gen, path = ckpt.generations(ck, ".p0")[0]
    meta = json.loads(bytes(
        ckpt._load_verified(path)["meta_json"]).decode())
    section = meta["ingest_offsets"]
    assert section["format"] == "partitioned"
    committed = {name: {"byte_offset": e["byte_offset"],
                        "records": e["records"]}
                 for name, e in section["partitions"].items()}
    last = max(windows, key=lambda r: r["seq"])
    assert committed == last["ingest_offsets"]
    assert sum(e["records"] for e in
               section["partitions"].values()) == N_EVENTS

    # Bit-identity vs the fixed topology recovered at the same
    # boundaries: the gang was killed, restarted, rescaled twice — and
    # still produced the reference stream.
    ref = _fixed_topology_reference(
        plog, tmp_path, [r["window"] for r in scale], max(seqs))
    assert proc.stdout == ref
