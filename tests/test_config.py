"""Config/CLI parsing tests (reference parity: ``Configuration.java:56-199``)."""

import pytest

from tpu_cooccurrence.config import Backend, Config, WindowUnit


def test_defaults_match_reference():
    cfg = Config.from_args(["-i", "x.csv", "-ws", "5"])
    assert cfg.item_cut == 500
    assert cfg.user_cut == 500
    assert cfg.top_k == 10
    assert cfg.window_unit is WindowUnit.MILLISECONDS
    assert cfg.buffer_timeout == 100
    assert cfg.skip_cuts is False
    assert cfg.seed is not None  # clock-derived like System.nanoTime()


def test_hex_seed():
    cfg = Config.from_args(["-i", "x", "-ws", "1", "-s", "0xC0FFEE"])
    assert cfg.seed == 0xC0FFEE
    cfg = Config.from_args(["-i", "x", "-ws", "1", "-s", "12345"])
    assert cfg.seed == 12345


def test_window_units():
    for name, millis in [("SECONDS", 1000), ("minutes", 60_000),
                         ("HOURS", 3_600_000), ("days", 86_400_000)]:
        cfg = Config.from_args(["-i", "x", "-ws", "2", "-wu", name])
        assert cfg.window_millis == 2 * millis


def test_unknown_window_unit_rejected():
    with pytest.raises(SystemExit):
        Config.from_args(["-i", "x", "-ws", "1", "-wu", "FORTNIGHTS"])


def test_input_required():
    with pytest.raises(SystemExit):
        Config.from_args(["-ws", "1"])


def test_window_size_required():
    with pytest.raises(SystemExit):
        Config.from_args(["-i", "x"])


def test_short_flags():
    cfg = Config.from_args(["-i", "x", "-ws", "1", "-ic", "7", "-uc", "9",
                            "-k", "3", "-sc", "-bt", "50"])
    assert cfg.item_cut == 7
    assert cfg.user_cut == 9
    assert cfg.top_k == 3
    assert cfg.skip_cuts is True
    assert cfg.buffer_timeout == 50


def test_top_k_positive_required():
    # Reference: ItemRowRescorerTwoInputStreamOperator.java:52-54.
    with pytest.raises(ValueError):
        Config(input="x", window_size=1, top_k=0)


def test_backend_parse():
    cfg = Config.from_args(["-i", "x", "-ws", "1", "--backend", "sharded",
                            "--num-shards", "4", "--num-items", "100"])
    assert cfg.backend is Backend.SHARDED
    assert cfg.num_shards == 4


def test_score_ladder_and_fixed_score_flags():
    from tpu_cooccurrence.config import Config

    cfg = Config.from_args(["-i", "x.csv", "-ws", "100",
                            "--backend", "sparse",
                            "--score-ladder", "16", "--fixed-score", "on"])
    assert cfg.score_ladder == 16
    assert cfg.fixed_score == "on"
    # Defaults: ladder deferred to the scorer (env or 4), fixed-score auto.
    cfg2 = Config.from_args(["-i", "x.csv", "-ws", "100"])
    assert cfg2.score_ladder is None
    assert cfg2.fixed_score == "auto"


def test_invalid_score_ladder_rejected_at_job_construction():
    import pytest

    from tpu_cooccurrence.config import Backend, Config
    from tpu_cooccurrence.job import CooccurrenceJob

    cfg = Config(window_size=10, seed=1, backend=Backend.SPARSE,
                 score_ladder=3)
    with pytest.raises(ValueError, match="power of two"):
        CooccurrenceJob(cfg)


def test_fixed_score_conflicts_rejected():
    import pytest

    from tpu_cooccurrence.config import Backend, Config
    from tpu_cooccurrence.job import CooccurrenceJob

    # Explicit on + per-window emission: refuse, don't silently downgrade.
    cfg = Config(window_size=10, seed=1, backend=Backend.SPARSE,
                 fixed_score="on", emit_updates=True)
    with pytest.raises(ValueError, match="emit-updates"):
        CooccurrenceJob(cfg)
    # Explicit on + sharded-sparse + emit-updates: refuse (the fused
    # rectangles are defer-only there too).
    cfg2 = Config(window_size=10, seed=1, backend=Backend.SPARSE,
                  fixed_score="on", num_shards=2, emit_updates=True)
    with pytest.raises(ValueError, match="emit-updates"):
        CooccurrenceJob(cfg2)
    # Bogus value: descriptive error, not a KeyError.
    cfg3 = Config(window_size=10, seed=1, backend=Backend.SPARSE,
                  fixed_score="yes")
    with pytest.raises(ValueError, match="auto|on|off"):
        CooccurrenceJob(cfg3)


def test_fixed_score_rejected_on_non_sparse_backends():
    import pytest

    from tpu_cooccurrence.config import Backend, Config
    from tpu_cooccurrence.job import CooccurrenceJob

    cfg = Config(window_size=10, seed=1, backend=Backend.DEVICE,
                 num_items=16, fixed_score="on")
    with pytest.raises(ValueError, match="only applies"):
        CooccurrenceJob(cfg)


def test_pallas_flag_plumbed_to_sharded_backends():
    """--pallas reaches both sharded scorers (the kernels run per shard
    inside their shard_map bodies)."""
    from tpu_cooccurrence.config import Backend, Config
    from tpu_cooccurrence.job import CooccurrenceJob
    from tpu_cooccurrence.parallel.sharded import ShardedScorer
    from tpu_cooccurrence.parallel.sharded_sparse import ShardedSparseScorer

    cfg = Config(window_size=10, seed=1, backend=Backend.SHARDED,
                 num_items=64, num_shards=2, pallas="on")
    job = CooccurrenceJob(cfg)
    assert isinstance(job.scorer, ShardedScorer)
    assert job.scorer.use_pallas is True
    # With pallas the vocab pads to a kernel-tile multiple.
    assert job.scorer.num_items % job.scorer.PALLAS_TILE == 0
    sp = Config(window_size=10, seed=1, backend=Backend.SPARSE,
                num_shards=2, pallas="on")
    job2 = CooccurrenceJob(sp)
    assert isinstance(job2.scorer, ShardedSparseScorer)
    assert job2.scorer.use_pallas is True


def test_fixed_score_honored_under_hybrid_alias():
    """--backend hybrid is a full sparse alias: sparse-only flags must be
    accepted (the alias is applied before flag validation)."""
    from tpu_cooccurrence.config import Backend, Config
    from tpu_cooccurrence.job import CooccurrenceJob
    from tpu_cooccurrence.state.sparse_scorer import SparseDeviceScorer

    cfg = Config(window_size=10, seed=1, backend=Backend.HYBRID,
                 fixed_score="off")
    job = CooccurrenceJob(cfg)
    assert isinstance(job.scorer, SparseDeviceScorer)
    assert job.scorer.fixed_shapes is False


# -- gang supervision flags (ISSUE 10) ---------------------------------


def test_gang_workers_validation():
    ok = Config(window_size=10, seed=1, backend=Backend.SHARDED,
                num_shards=2, gang_workers=2)
    assert ok.gang_workers == 2
    with pytest.raises(ValueError, match="gang of one"):
        Config(window_size=10, seed=1, backend=Backend.SHARDED,
               gang_workers=1)
    with pytest.raises(ValueError, match="assigns"):
        Config(window_size=10, seed=1, backend=Backend.SHARDED,
               gang_workers=2, coordinator="h:1", num_processes=2,
               process_id=0)
    with pytest.raises(ValueError, match="process-continuously"):
        Config(window_size=10, seed=1, backend=Backend.SHARDED,
               gang_workers=2, process_continuously=True)
    with pytest.raises(ValueError, match="replica fleet"):
        Config(window_size=10, seed=1, backend=Backend.SHARDED,
               gang_workers=2, serve_port=0)


def test_gang_workers_needs_multihost_backend():
    with pytest.raises(ValueError, match="multi-controller"):
        Config(window_size=10, seed=1, gang_workers=2)  # device backend
    with pytest.raises(ValueError, match="multi-controller"):
        Config(window_size=10, seed=1, backend=Backend.SPARSE,
               gang_workers=2)  # sparse needs num_shards > 1
    Config(window_size=10, seed=1, backend=Backend.SPARSE, num_shards=4,
           gang_workers=2)


def test_gang_timing_flags_validation():
    with pytest.raises(ValueError, match="gang-heartbeat-s"):
        Config(window_size=10, seed=1, gang_heartbeat_s=0)
    with pytest.raises(ValueError, match="gang-stale-after-s"):
        Config(window_size=10, seed=1, gang_stale_after_s=-1)
    with pytest.raises(ValueError, match="collective-timeout-s"):
        Config(window_size=10, seed=1, collective_timeout_s=-1)


def test_gang_workers_with_restart_budget_and_watchdog():
    # The gang reuses --restart-on-failure as its attempt budget and
    # may run the journal-staleness watchdog without a single-process
    # supervisor.
    Config(window_size=10, seed=1, backend=Backend.SHARDED,
           num_shards=2, gang_workers=2, restart_on_failure=3,
           watchdog_stale_after_s=5.0, journal="/tmp/j.jsonl")
    with pytest.raises(ValueError, match="restart-on-failure"):
        Config(window_size=10, seed=1, watchdog_stale_after_s=5.0,
               journal="/tmp/j.jsonl")


def test_multihost_pipeline_now_accepted_partition_sampling_not():
    # ISSUE 10 relaxed the blanket multi-host pipeline rejection: the
    # scorer worker issues collectives serially in window order. The
    # partitioned sampler's sampling-thread allgather still conflicts.
    Config(window_size=10, seed=1, backend=Backend.SHARDED,
           coordinator="h:1", num_processes=2, process_id=0,
           pipeline_depth=2)
    with pytest.raises(ValueError, match="partition-sampling"):
        Config(window_size=10, seed=1, backend=Backend.SHARDED,
               coordinator="h:1", num_processes=2, process_id=0,
               pipeline_depth=2, partition_sampling=True)
