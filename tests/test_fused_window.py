"""Fused one-dispatch window path (--fused-window): parity + routing.

The contract under test (ISSUE 6): with the fused path forced on, every
routable window runs expansion + count update + row sums + LLR + top-K
as ONE device program fed by the basket uplink, and the results are
BIT-identical to the chained path (and match the host oracle to the
usual f32/f64 tolerance with the tie exemption) at pipeline depths 0
and 2 — including the ladder edges: empty windows, single-pair windows,
windows exactly at an ops-bucket boundary, and windows overflowing into
the next bucket. Non-routable windows (oversized for the chunk budget)
must fall back to the chained path with identical results, and the
PR-5 scorer circuit breaker must fail over to the host oracle
identically whether the fused path is on or off.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

import tpu_cooccurrence.ops.device_scorer as ds
from tpu_cooccurrence.config import Backend, Config
from tpu_cooccurrence.job import CooccurrenceJob
from tpu_cooccurrence.observability.registry import REGISTRY
from tpu_cooccurrence.ops.aggregate import aggregate_window_coo
from tpu_cooccurrence.ops.pallas_score import pallas_expand_baskets
from tpu_cooccurrence.sampling.reservoir import (BasketBatch,
                                                 PairDeltaBatch,
                                                 UserReservoirSampler)

from test_pipeline import assert_latest_close, relabel_first_appearance

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ENV = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")


def _run_job(users, items, ts, chunk=97, **overrides):
    kw = dict(window_size=10, seed=0xBEEF, backend=Backend.DEVICE,
              development_mode=True)
    kw.update(overrides)
    job = CooccurrenceJob(Config(**kw))
    for lo in range(0, len(users), chunk):
        job.add_batch(users[lo:lo + chunk], items[lo:lo + chunk],
                      ts[lo:lo + chunk])
    job.finish()
    return job


def _table(job):
    return {k: job.latest[k] for k in job.latest}


def _fold(src, dst, delta):
    s, d, v = aggregate_window_coo(np.asarray(src, dtype=np.int64),
                                   np.asarray(dst, dtype=np.int64),
                                   np.asarray(delta, dtype=np.int64))
    keep = v != 0
    return list(zip(s[keep].tolist(), d[keep].tolist(), v[keep].tolist()))


def _ladder_edge_stream():
    """A stream whose windows hit the ops-bucket ladder edges.

    Window 1 (ts 5): first-ever items only — every op has len 0, so the
    window fires with events but ZERO pairs (the empty edge). Window 2
    (ts 15): one user's second item — a single op of len 1 (the
    single-pair edge). Window 3 (ts 25): exactly 64 append ops (the
    minimum ops bucket, exactly-at-boundary). Window 4 (ts 35): 65 ops
    — overflow into the 128 bucket. Window 5 (ts 45): draws against
    full reservoirs (user_cut=4) — the replacement two-op ±1 form.
    """
    users, items, ts = [], [], []

    def ev(u, i, t):
        users.append(u)
        items.append(i)
        ts.append(t)

    for u in range(70):                      # window 1: all first items
        ev(u, 1000 + u, 5)
    ev(0, 100, 15)                           # window 2: one len-1 op
    for u in range(64):                      # window 3: exactly 64 ops
        ev(u, 200 + u, 25)
    for u in range(65):                      # window 4: 65 ops
        ev(u, 300 + u, 35)
    for k in range(30):                      # window 5: replacements
        ev(k % 4, 400 + k, 45)
    ev(0, 999, 65)                           # flush window 5
    users = relabel_first_appearance(np.asarray(users))
    items = relabel_first_appearance(np.asarray(items))
    return users, np.asarray(items), np.asarray(ts, dtype=np.int64)


# -- kernel-level parity (the registered parity test for
#    pallas_expand_baskets, pinned by cooclint pallas-kernel-registry) --


def test_pallas_expand_baskets_matches_host_expansion():
    """The expansion kernel's folded COO output equals the host
    expansion (BasketBatch.to_pairs) fold, across append ops (skip=-1),
    replacement op pairs (skip=slot, ±1), zero-length ops, and pad
    rows; pad/invalid lanes carry the (0, 0, 0) scatter no-op."""
    rng = np.random.default_rng(42)
    n_ops, w = 16, 128
    baskets = rng.integers(1, 50, size=(n_ops, w)).astype(np.int32)
    lens = np.array([0, 1, 5, 7] * 4, dtype=np.int32)
    skips = np.full(n_ops, -1, dtype=np.int32)
    skips[2::4] = 3                       # replacement-style exclusions
    signs = np.ones(n_ops, dtype=np.int32)
    signs[3::4] = -1
    new = rng.integers(50, 60, size=n_ops).astype(np.int32)
    b = BasketBatch(new, baskets, lens, skips, signs)

    src, dst, delta = pallas_expand_baskets(
        baskets, new.reshape(-1, 1), lens.reshape(-1, 1),
        skips.reshape(-1, 1), signs.reshape(-1, 1), interpret=True)
    src, dst, delta = (np.asarray(src).ravel(), np.asarray(dst).ravel(),
                      np.asarray(delta).ravel())
    lanes_used = (delta != 0).sum()
    assert lanes_used == len(b) == len(b.to_pairs())
    # Every zero-delta lane is the full no-op triple.
    idle = delta == 0
    assert not src[idle].any() and not dst[idle].any()
    p = b.to_pairs()
    assert _fold(src, dst, delta) == _fold(p.src, p.dst, p.delta)


def test_pallas_expand_baskets_rejects_bad_shapes():
    ok = np.zeros((8, 128), np.int32)
    meta = np.zeros((8, 1), np.int32)
    with pytest.raises(ValueError, match="multiple of 8"):
        pallas_expand_baskets(ok[:6], meta[:6], meta[:6], meta[:6],
                              meta[:6], interpret=True)
    with pytest.raises(ValueError, match="multiple of 128"):
        pallas_expand_baskets(np.zeros((8, 64), np.int32), meta, meta,
                              meta, meta, interpret=True)


# -- sampler encoding ---------------------------------------------------


def test_sampler_basket_mode_matches_expanded_pairs():
    """Twin samplers over the same stream: the basket encoding's
    expanded pair multiset equals the COO path's, window by window,
    including replacement windows (the two-op ±1 form) and the
    feedback stream."""
    rng = np.random.default_rng(7)
    a = UserReservoirSampler(user_cut=4, seed=123, skip_cuts=False)
    b = UserReservoirSampler(user_cut=4, seed=123, skip_cuts=False)
    b.emit_baskets = True
    for _ in range(12):
        n = int(rng.integers(5, 40))
        users = rng.integers(0, 6, n)
        items = rng.integers(0, 30, n)
        sampled = rng.random(n) < 0.9
        pa, fa = a.fire(users, items, sampled)
        pb, fb = b.fire(users, items, sampled)
        assert isinstance(pb, BasketBatch)
        assert len(pa) == len(pb)
        assert _fold(pa.src, pa.dst, pa.delta) == \
            _fold(pb.src, pb.dst, pb.delta)
        np.testing.assert_array_equal(fa, fb)
    # Reservoir state is identical too: the encoding is output-only.
    np.testing.assert_array_equal(a.hist_len, b.hist_len)
    np.testing.assert_array_equal(a.clean_hist(6), b.clean_hist(6))


# -- end-to-end parity: ladder edges, both backends, depths 0 + 2 ------


@pytest.mark.parametrize("depth", [0, 2])
def test_fused_bit_identical_to_chained_at_ladder_edges(depth):
    users, items, ts = _ladder_edge_stream()
    kw = dict(user_cut=4, item_cut=500, pipeline_depth=depth)
    chained = _run_job(users, items, ts, fused_window="off", **kw)
    fused = _run_job(users, items, ts, fused_window="on", **kw)
    # Bit-identical: same rows, same ids, same float32 scores.
    assert _table(chained) == _table(fused)
    assert chained.counters.as_dict() == fused.counters.as_dict()
    assert chained.windows_fired == fused.windows_fired


@pytest.mark.parametrize("depth", [0, 2])
def test_fused_matches_host_oracle(depth):
    users, items, ts = _ladder_edge_stream()
    kw = dict(user_cut=4, item_cut=500, pipeline_depth=depth)
    oracle = _run_job(users, items, ts, backend=Backend.ORACLE, **kw)
    fused = _run_job(users, items, ts, fused_window="on", **kw)
    # f32 device vs f64 oracle: scores to tolerance, ids exact wherever
    # the row's score gaps exceed it (the lo>0-style tie exemption).
    assert_latest_close(_table(oracle), _table(fused))


def test_fused_bit_identical_with_pallas_score_and_int16():
    users, items, ts = _ladder_edge_stream()
    for extra in (dict(pallas="on"), dict(count_dtype="int16")):
        kw = dict(user_cut=4, item_cut=500, **extra)
        chained = _run_job(users, items, ts, fused_window="off", **kw)
        fused = _run_job(users, items, ts, fused_window="on", **kw)
        assert _table(chained) == _table(fused), extra


def test_fused_emit_updates_mode_bit_identical():
    users, items, ts = _ladder_edge_stream()
    kw = dict(user_cut=4, item_cut=500, emit_updates=True)
    chained = _run_job(users, items, ts, fused_window="off", **kw)
    fused = _run_job(users, items, ts, fused_window="on", **kw)
    assert _table(chained) == _table(fused)


# -- routing and dispatch counts ---------------------------------------


class _FusedCounter:
    """Counting shims around the device scorer's jitted entry points."""

    TRACKED = ("_fused_window_emit", "_fused_window_defer", "_update_coo",
               "_update_coo_u16", "_update_coo_chunked",
               "_update_coo_u16_chunked", "_score")

    def __init__(self, monkeypatch):
        self.counts = {name: 0 for name in self.TRACKED}
        for name in self.TRACKED:
            monkeypatch.setattr(ds, name, self._wrap(name,
                                                     getattr(ds, name)))

    def _wrap(self, name, fn):
        def counted(*args, **kwargs):
            self.counts[name] += 1
            return fn(*args, **kwargs)
        return counted

    @property
    def fused(self):
        return (self.counts["_fused_window_emit"]
                + self.counts["_fused_window_defer"])

    @property
    def chained(self):
        return sum(self.counts[n] for n in self.TRACKED
                   if n.startswith("_update")) + self.counts["_score"]


def test_fused_window_is_one_dispatch(monkeypatch):
    """Every fused-routable window is exactly ONE jitted call — no
    separate update or score dispatch ever runs on the fused path."""
    counter = _FusedCounter(monkeypatch)
    users, items, ts = _ladder_edge_stream()
    job = _run_job(users, items, ts, user_cut=4, fused_window="on")
    assert counter.chained == 0, counter.counts
    # Windows 2-5 carry pairs (window 1 is the all-first-items empty
    # edge): one fused dispatch each.
    assert counter.fused == 4, counter.counts
    assert job.windows_fired >= 5


def test_chained_dispatch_path_unchanged_with_fused_off(monkeypatch):
    """--fused-window off (the default) keeps the seed's compiled-shape
    ladder: the exact chained entry points run, and the fused program
    is never compiled or dispatched — the dispatch/compile-count
    contract for existing configurations."""
    counter = _FusedCounter(monkeypatch)
    users, items, ts = _ladder_edge_stream()
    _run_job(users, items, ts, user_cut=4, fused_window="off")
    assert counter.fused == 0, counter.counts
    updates = sum(counter.counts[n] for n in counter.TRACKED
                  if n.startswith("_update"))
    assert updates >= 4, counter.counts
    assert counter.counts["_score"] >= 4, counter.counts


def test_fused_oversize_window_falls_back_chained(monkeypatch):
    """A window whose padded expansion lanes exceed max_pairs_per_step
    routes chained (per-window, results identical); the chunk budget is
    honored rather than silently inflated."""
    users, items, ts = _ladder_edge_stream()
    kw = dict(user_cut=4, item_cut=500, max_pairs_per_step=1 << 14)
    chained = _run_job(users, items, ts, fused_window="off", **kw)
    counter = _FusedCounter(monkeypatch)
    fused = _run_job(users, items, ts, fused_window="on", **kw)
    # 2 * n_cap * l_cap = 16384 lanes at the minimum buckets fits the
    # budget exactly, so the <=64-op windows stay fused; the 65-op
    # window (128-op bucket, 32768 lanes) falls back to chained.
    assert counter.fused == 3, counter.counts
    assert counter.chained >= 2, counter.counts
    assert _table(chained) == _table(fused)


def test_fused_registry_counters_and_journal(tmp_path):
    REGISTRY.reset()
    users, items, ts = _ladder_edge_stream()
    jpath = tmp_path / "journal.jsonl"
    _run_job(users, items, ts, user_cut=4, fused_window="on",
             journal=str(jpath))
    assert REGISTRY.gauge("cooc_fused_dispatches_total").get() == 4
    assert REGISTRY.gauge("cooc_chained_dispatches_total").get() == 0
    from tpu_cooccurrence.observability.journal import (read_records,
                                                        validate_record)

    recs = [r for r in read_records(str(jpath)) if "seq" in r]
    for r in recs:
        validate_record(r)
    flags = [r["fused"] for r in recs]
    assert flags.count(1) == 4            # the four pair-carrying windows
    assert set(flags) <= {0, 1}
    # The wall-time split histograms saw the same windows.
    fused_hist = REGISTRY.histogram("cooc_window_score_seconds_fused")
    assert fused_hist.count == 4


# -- config validation --------------------------------------------------


def test_fused_window_config_validation():
    with pytest.raises(ValueError, match="device or sparse"):
        Config(window_size=10, backend=Backend.ORACLE, fused_window="on")
    with pytest.raises(ValueError, match="tumbling"):
        Config(window_size=10, window_slide=5, fused_window="on")
    with pytest.raises(ValueError, match="auto"):
        Config(window_size=10, fused_window="sometimes")
    # Single-process sparse accepts a forced 'on' since the fused sparse
    # window landed (its own validation lives in test_fused_sparse.py);
    # auto still rides along anywhere.
    Config(window_size=10, backend=Backend.SPARSE, fused_window="on")
    Config(window_size=10, backend=Backend.SHARDED, fused_window="auto")


# -- satellite: COO chunk pad-slot guard --------------------------------


def test_check_coo_chunk_guard():
    coo = np.zeros((3, 8), dtype=np.int32)
    coo[:, :5] = 1
    ds.check_coo_chunk(coo, 5)            # clean chunk passes
    with pytest.raises(AssertionError, match="silently truncated"):
        ds.check_coo_chunk(coo, 9)
    coo[2, 6] = 1                          # nonzero pad slot
    with pytest.raises(AssertionError, match="pad slots"):
        ds.check_coo_chunk(coo, 5)


# -- chaos: breaker failover with the fused path on ---------------------


def test_fused_breaker_failover_identical(tmp_path):
    """An injected dispatch failure (the scorer_breaker site inside the
    device scorer — where an injected `scorer_dispatch`-class fault
    lands once the window reaches the scorer) trips the PR-5 circuit
    breaker mid-run with --fused-window on; the run completes on the
    host-oracle fallback and its stdout is IDENTICAL to the same
    faulted run on the chained path — the fallback consumes the basket
    payload through the same pair stream."""
    from test_cli import write_stream

    f = tmp_path / "in.csv"
    write_stream(f, n=600)

    def run(fused, journal):
        proc = subprocess.run(
            [sys.executable, "-m", "tpu_cooccurrence.cli", "-i", str(f),
             "-ws", "40", "-ic", "8", "-uc", "5", "-s", "0xC0FFEE",
             "--backend", "device", "--fused-window", fused,
             "--journal", journal,
             "--scorer-breaker-threshold", "1",
             "--scorer-breaker-probe-windows", "3",
             "--inject-fault", "scorer_breaker:3:exception"],
            capture_output=True, text=True, env=ENV, cwd=REPO,
            timeout=600)
        assert proc.returncode == 0, proc.stderr[-800:]
        return proc.stdout

    out_fused = run("on", str(tmp_path / "j_fused.jsonl"))
    out_chained = run("off", str(tmp_path / "j_chained.jsonl"))
    assert out_fused, "run completed but emitted no results"
    assert out_fused == out_chained
    from tpu_cooccurrence.observability.journal import read_records

    recs = [r for r in read_records(str(tmp_path / "j_fused.jsonl"))
            if "breaker_state" in r]
    states = [r["breaker_state"] for r in recs]
    assert "open" in states, states       # the trip is journaled
    assert states[-1] == "closed", states  # half-open probe recovered
    # A fallback-scored window is never a fused dispatch — the breaker
    # wrapper shadows the primary's stale flag.
    for r in recs:
        if r["breaker_state"] == "open" and r.get("rows_scored"):
            assert r.get("fused") == 0, r
