"""Incremental checkpoints + continuous delta log (ISSUE 12).

The contracts under test:

* **Byte-identical reconstruction** — an incremental generation's
  ``base + delta[B+1..G]`` replay produces EXACTLY the arrays a full
  generation-``G`` checkpoint holds (same values, same dtypes), across
  StateStores (Direct / Tiered / ShardedRescale), cell dtypes
  (int32/int16/int8 incl. wide side-table rows) and wire formats
  (raw/packed); a job restored from the chain continues bit-identically
  to one restored from a full checkpoint.
* **Chain robustness** — ``step_back`` from a delta generation lands on
  a restorable prefix; retention never orphans a base or intermediate
  delta a retained generation chains through; a corrupt delta is
  quarantined ``*.corrupt`` and restore falls back one committed
  generation (the PR-3 torn-npz contract extended to chains).
* **Commit bytes scale with churn** — steady-state delta generations
  commit a fraction of the full-checkpoint bytes (the bench
  ``checkpoint`` arm carries the headline ratio on the churn stream;
  this file pins the direction on a small stream).
* **Delta log consumption** — ``read_delta_stream`` yields the
  documented records; replaying ``iter_topk`` over a base top-K
  snapshot reproduces the writer's final table (the replica catch-up
  contract, ROADMAP #2).
"""

import json
import os

import numpy as np
import pytest

from tpu_cooccurrence.config import Backend, Config
from tpu_cooccurrence.job import CooccurrenceJob
from tpu_cooccurrence.observability.journal import validate_record
from tpu_cooccurrence.state import checkpoint as ckpt
from tpu_cooccurrence.state import delta as deltalog
from tpu_cooccurrence.state.delta import (DeltaCorrupt, DirtyRowLog,
                                          decode_delta, encode_delta,
                                          read_delta_file,
                                          read_delta_stream)

from test_pipeline import random_stream
from test_state_store import assert_latest_identical


def cfg(tmp_path, subdir="ckpt", incremental=True, **kw):
    kw.setdefault("backend", Backend.SPARSE)
    kw.setdefault("window_size", 10)
    kw.setdefault("seed", 0xABCD)
    kw.setdefault("item_cut", 5)
    kw.setdefault("user_cut", 3)
    kw.setdefault("development_mode", True)
    kw.setdefault("checkpoint_every_windows", 2)
    kw.setdefault("checkpoint_retain", 50)
    return Config(checkpoint_dir=str(tmp_path / subdir),
                  checkpoint_incremental=incremental, **kw)


def feed(job, users, items, ts, chunk=97):
    for lo in range(0, len(users), chunk):
        job.add_batch(users[lo:lo + chunk], items[lo:lo + chunk],
                      ts[lo:lo + chunk])


#: Job-level row-indexed arrays the delta chain reconstructs alongside
#: the scorer blob (reservoir table + append-only vocabs).
AUX_KEYS = ("item_vocab", "user_vocab", "hist", "hist_len", "total",
            "draws")


def canonical_arrays(directory, suffix=""):
    """The newest generation's big arrays, chain-resolved when
    incremental — exactly what restore will hand the scorer."""
    gen, path = ckpt.generations(directory, suffix)[0]
    data = ckpt._load_verified(path)
    meta = json.loads(bytes(data["meta_json"]).decode())
    if meta.get("ckpt_delta"):
        blob, latest, aux = ckpt._resolve_chain(directory, suffix, gen,
                                                meta)
        data.update({f"scorer_{k}": v for k, v in blob.items()})
        for k, v in zip(ckpt._LATEST_KEYS, latest):
            data[k] = v
        data.update(aux)
    else:
        ckpt._decode_codec(data, meta)
    return gen, {k: np.asarray(v) for k, v in data.items()
                 if k.startswith("scorer_") or k.startswith("latest_")
                 or k in AUX_KEYS}


def assert_same_arrays(a, b):
    assert set(a) == set(b), (set(a) ^ set(b))
    for k in a:
        np.testing.assert_array_equal(a[k], b[k], err_msg=k)
        assert a[k].dtype == b[k].dtype, (k, a[k].dtype, b[k].dtype)


# -- byte-identical reconstruction -------------------------------------


@pytest.mark.parametrize("cell_dtype,wire_format", [
    ("int32", "raw"),
    ("int16", "packed"),
    ("int8", "packed"),
])
def test_chain_restore_byte_identical(tmp_path, cell_dtype, wire_format):
    """Incremental vs full runs of the same stream: the chain-resolved
    arrays equal the full checkpoint's, the restored jobs continue
    bit-identically — across cell dtypes (int8 forces wide side-table
    rows) and both checkpoint codecs."""
    users, items, ts = random_stream(31, n=900, n_items=70, n_users=28)
    half = 430
    kw = dict(cell_dtype=cell_dtype, wire_format=wire_format)
    for inc, sub in ((True, "inc"), (False, "full")):
        a = CooccurrenceJob(cfg(tmp_path, sub, incremental=inc, **kw))
        feed(a, users[:half], items[:half], ts[:half])
        a.checkpoint()
    inc_dir = str(tmp_path / "inc")
    assert deltalog.delta_generations(inc_dir, ""), \
        "no delta generation landed — the incremental path never engaged"
    _, arrs_inc = canonical_arrays(inc_dir)
    _, arrs_full = canonical_arrays(str(tmp_path / "full"))
    # The tiered recency arrays only exist under spill; none here.
    assert_same_arrays(arrs_inc, arrs_full)

    outs = []
    for sub in ("inc", "full"):
        b = CooccurrenceJob(cfg(tmp_path, sub, incremental=(sub == "inc"),
                                **kw))
        b.restore()
        feed(b, users[half:], items[half:], ts[half:])
        b.finish()
        outs.append(b)
    assert_latest_identical(outs[0].latest, outs[1].latest)
    assert outs[0].counters.as_dict() == outs[1].counters.as_dict()


def test_chain_restore_tiered_store(tmp_path):
    """Spill on + incremental: arena cells merge into the delta records
    and the persisted recency clock rides the generation — restored
    state matches the full-checkpoint variant exactly."""
    users, items, ts = random_stream(32, n=900, n_items=70, n_users=28)
    half = 430
    kw = dict(spill_threshold_windows=2, spill_target_hbm_frac=0.0)
    for inc, sub in ((True, "inc"), (False, "full")):
        a = CooccurrenceJob(cfg(tmp_path, sub, incremental=inc, **kw))
        feed(a, users[:half], items[:half], ts[:half])
        a.checkpoint()
        if inc:
            assert len(a.scorer.store.arena), "nothing spilled: vacuous"
    assert deltalog.delta_generations(str(tmp_path / "inc"), "")
    _, arrs_inc = canonical_arrays(str(tmp_path / "inc"))
    _, arrs_full = canonical_arrays(str(tmp_path / "full"))
    assert_same_arrays(arrs_inc, arrs_full)
    b = CooccurrenceJob(cfg(tmp_path, "inc", **kw))
    b.restore()
    c = CooccurrenceJob(cfg(tmp_path, "full", incremental=False, **kw))
    c.restore()
    # Recency resumed identically from both (the tier_* arrays ride
    # the small-state npz either way).
    assert b.scorer.store.clock == c.scorer.store.clock > 0
    np.testing.assert_array_equal(b.scorer.store.last_touch,
                                  c.scorer.store.last_touch)
    feed(b, users[half:], items[half:], ts[half:])
    b.finish()
    feed(c, users[half:], items[half:], ts[half:])
    c.finish()
    assert_latest_identical(b.latest, c.latest)


def test_chain_restore_sharded_rescale(tmp_path):
    """Single-process sharded-sparse (ShardedRescaleStore): a chain
    written at N=2 shards restores at M=3 bit-identically to a full
    checkpoint restored at M=3 (rescale works FROM the reconstruction)."""
    users, items, ts = random_stream(33, n=800, n_items=60, n_users=24)
    half = 390
    for inc, sub in ((True, "inc"), (False, "full")):
        a = CooccurrenceJob(cfg(tmp_path, sub, incremental=inc,
                                num_shards=2))
        feed(a, users[:half], items[:half], ts[:half])
        a.checkpoint()
    assert deltalog.delta_generations(str(tmp_path / "inc"), "")
    _, arrs_inc = canonical_arrays(str(tmp_path / "inc"))
    _, arrs_full = canonical_arrays(str(tmp_path / "full"))
    assert_same_arrays(arrs_inc, arrs_full)
    outs = []
    for sub in ("inc", "full"):
        b = CooccurrenceJob(cfg(tmp_path, sub, incremental=(sub == "inc"),
                                num_shards=3))
        b.restore()
        feed(b, users[half:], items[half:], ts[half:])
        b.finish()
        outs.append(b)
    assert_latest_identical(outs[0].latest, outs[1].latest)


# -- commit bytes ------------------------------------------------------


def churn_stream(windows=18, users_per=30, events_per=300, n_items=900,
                 alpha=1.1, drift=60, seed=11, window_ms=100):
    """Small cousin of the bench ``_longtail_churn_stream``: per-window
    user cohorts + catalog drift, the two shapes that make rows
    genuinely go cold — and therefore make per-generation churn a
    FRACTION of accumulated state (a uniform stream touches everything
    every window, and deltas rightly cannot beat a full rewrite there)."""
    rng = np.random.default_rng(seed)
    ranks = np.arange(1, n_items + 1, dtype=np.float64)
    p = ranks ** -alpha
    p /= p.sum()
    us, its, tss = [], [], []
    for w in range(windows):
        u = w * users_per + rng.integers(0, users_per, events_per)
        i = (rng.choice(n_items, size=events_per, p=p)
             + w * drift) % n_items
        t = w * window_ms + np.sort(rng.integers(0, window_ms, events_per))
        us.append(u.astype(np.int64))
        its.append(i.astype(np.int64))
        tss.append(t.astype(np.int64))
    return (np.concatenate(us), np.concatenate(its),
            np.concatenate(tss))


def test_delta_commit_bytes_scale_with_churn(tmp_path):
    """On the churn stream, per-generation commit bytes (npz + delta)
    fall ever further below the full-checkpoint bytes at the SAME
    generation as state accumulates — commit cost tracks churn, not
    vocab. The bench ``checkpoint`` arm carries the at-scale headline;
    this pins the direction and the trend."""
    users, items, ts = churn_stream()
    sizes = {}
    for inc, sub in ((True, "inc"), (False, "full")):
        job = CooccurrenceJob(cfg(tmp_path, sub, incremental=inc,
                                  window_size=100,
                                  checkpoint_compact_ratio=1e9))
        feed(job, users, items, ts, chunk=300)
        job.finish()
        d = str(tmp_path / sub)
        per = {}
        for g, p in ckpt.generations(d, ""):
            b = os.path.getsize(p)
            dp = deltalog.delta_path(d, "", g)
            if os.path.exists(dp):
                b += os.path.getsize(dp)
            per[g] = b
        sizes[sub] = per
    common = sorted(set(sizes["inc"]) & set(sizes["full"]))
    assert len(common) >= 8
    ratios = [sizes["inc"][g] / sizes["full"][g] for g in common]
    # Steady state: clearly below full, and trending down as the gap
    # between churn and accumulated state widens.
    assert ratios[-1] < 0.8, ratios
    assert max(ratios[-3:]) < 0.85, ratios
    assert np.mean(ratios[-3:]) < np.mean(ratios[2:5]), ratios


# -- chain robustness --------------------------------------------------


def _build_chain(tmp_path, **kw):
    users, items, ts = random_stream(35, n=1000, n_items=70, n_users=26)
    kw.setdefault("checkpoint_compact_ratio", 1e9)
    job = CooccurrenceJob(cfg(tmp_path, **kw))
    feed(job, users, items, ts)
    job.finish()
    return job, str(tmp_path / "ckpt"), (users, items, ts)


@pytest.fixture(scope="module")
def chain_repo(tmp_path_factory):
    """One shared base+delta chain for the read-only / copy-and-mutate
    tests (building a fresh chain per test is the file's main wall
    cost; tests that need a different cadence or retain build their
    own)."""
    tmp = tmp_path_factory.mktemp("chain")
    _job, d, stream = _build_chain(tmp)
    return tmp, d, stream


def _chain_copy(tmp_path, chain_repo):
    import shutil

    shutil.copytree(chain_repo[1], tmp_path / "ckpt")
    return str(tmp_path / "ckpt")


def test_step_back_from_delta_generation(tmp_path, chain_repo):
    d = _chain_copy(tmp_path, chain_repo)
    top = ckpt.generations(d, "")[0][0]
    assert top in deltalog.delta_generations(d, "")
    retired = ckpt.step_back(d)
    assert retired == top
    assert os.path.exists(os.path.join(d, f"state.{top}.npz.rolledback"))
    assert os.path.exists(deltalog.delta_path(d, "", top) + ".rolledback")
    b = CooccurrenceJob(cfg(tmp_path))
    b.restore()  # the prefix chain is restorable
    assert b.windows_fired > 0
    gen = int(json.loads(
        (tmp_path / "ckpt" / "meta.json").read_text())["windows_fired"])
    assert gen >= b.windows_fired


def test_retention_never_orphans_chain(tmp_path):
    """retain=2 with an ever-growing chain: the base (and every
    intermediate delta) survives past the numeric retain window while a
    retained generation still chains through it, and restore works."""
    job, d, _stream = _build_chain(tmp_path, checkpoint_retain=2)
    gens = [g for g, _p in ckpt.generations(d, "")]
    assert len(gens) > 2, "retention deleted chain members"
    base, chain = ckpt.chain_of(d, "", gens[0])
    assert base == min(gens), "the chain's base aged out"
    for g in chain:
        assert os.path.exists(deltalog.delta_path(d, "", g))
    b = CooccurrenceJob(cfg(tmp_path, checkpoint_retain=2))
    b.restore()
    assert b.windows_fired > 0


def test_retention_drops_pre_compaction_chain(tmp_path):
    """After a ratio-triggered compaction the OLD chain ages out: only
    generations the retained set chains through survive."""
    users, items, ts = random_stream(36, n=1200, n_items=80, n_users=28)
    job = CooccurrenceJob(cfg(tmp_path, checkpoint_retain=2,
                              checkpoint_compact_ratio=0.25))
    feed(job, users, items, ts)
    job.finish()
    d = str(tmp_path / "ckpt")
    gens = [g for g, _p in ckpt.generations(d, "")]
    base, _chain = ckpt.chain_of(d, "", gens[0])
    assert min(gens) >= min(base, gens[1] if len(gens) > 1 else gens[0])
    b = CooccurrenceJob(cfg(tmp_path, checkpoint_retain=2))
    b.restore()
    assert b.windows_fired > 0


def test_corrupt_delta_quarantined_falls_back(tmp_path, chain_repo):
    """Flip bytes inside the newest delta: restore quarantines it as
    *.corrupt and lands exactly one committed generation back."""
    d = _chain_copy(tmp_path, chain_repo)
    top = ckpt.generations(d, "")[0][0]
    dpath = deltalog.delta_path(d, "", top)
    raw = bytearray(open(dpath, "rb").read())
    raw[len(raw) // 2] ^= 0xFF
    with open(dpath, "wb") as f:
        f.write(bytes(raw))
    b = CooccurrenceJob(cfg(tmp_path))
    b.restore()
    assert os.path.exists(dpath + ".corrupt")
    assert not os.path.exists(dpath)
    from tpu_cooccurrence.observability.registry import REGISTRY
    assert REGISTRY.gauge(ckpt.QUARANTINE_GAUGE).get() >= 1
    # The restored generation is the previous one.
    from tpu_cooccurrence.observability.registry import REGISTRY as R
    assert int(R.gauge(ckpt.GENERATION_GAUGE).get()) == top - 1


def test_missing_base_breaks_chain_to_older_full(tmp_path, chain_repo):
    """Deleting the base npz makes every chained generation
    unrestorable — restore raises rather than fabricating state, and
    nothing is quarantined for a merely-missing link."""
    d = _chain_copy(tmp_path, chain_repo)
    top = ckpt.generations(d, "")[0][0]
    base, chain = ckpt.chain_of(d, "", top)
    os.remove(os.path.join(d, f"state.{base}.npz"))
    b = CooccurrenceJob(cfg(tmp_path))
    with pytest.raises(ckpt.CheckpointCorrupt):
        b.restore()
    assert not any(n.endswith(".corrupt") for n in os.listdir(d))


def test_compaction_ratio_trigger_and_gauge(tmp_path):
    """A tiny compact ratio forces a full base every save (compactions
    counted); a huge one lets the chain grow."""
    from tpu_cooccurrence.observability.registry import REGISTRY
    REGISTRY.gauge(ckpt.COMPACTIONS_GAUGE).set(0)
    users, items, ts = random_stream(37, n=700, n_items=60, n_users=24)
    job = CooccurrenceJob(cfg(tmp_path, "tiny",
                              checkpoint_compact_ratio=1e-9))
    feed(job, users, items, ts)
    job.finish()
    d = str(tmp_path / "tiny")
    # Only the very first post-base save may ride an empty chain; every
    # later one compacts (chain bytes 0 is never > 0 * ratio... the
    # first delta lands, then triggers compaction next save).
    assert REGISTRY.gauge(ckpt.COMPACTIONS_GAUGE).get() >= 1
    assert len(deltalog.delta_generations(d, "")) <= 1 + len(
        ckpt.generations(d, ""))


def test_anchor_mismatch_forces_full(tmp_path, chain_repo):
    """A fresh job saving into a directory with existing generations it
    never restored writes a FULL base first (the dirty log is not
    anchored at the newest on-disk generation) — only its OWN
    subsequent saves may chain off that base."""
    d = _chain_copy(tmp_path, chain_repo)
    users, items, ts = chain_repo[2]
    prev_top = ckpt.generations(d, "")[0][0]
    fresh = CooccurrenceJob(cfg(tmp_path, checkpoint_every_windows=0))
    half = 300
    feed(fresh, users[:half], items[:half], ts[:half])
    fresh.checkpoint()  # first save: anchor (-1) != prev_top -> full
    fresh.add_batch(users[half:half + 200], items[half:half + 200],
                    ts[half:half + 200])
    fresh.checkpoint()  # second save: anchored at its own base -> delta
    dgens = deltalog.delta_generations(d, "")
    assert prev_top + 1 not in dgens, "unanchored save wrote a delta"
    assert prev_top + 2 in dgens


def test_dirty_log_overflow_forces_full(tmp_path, monkeypatch):
    monkeypatch.setattr(DirtyRowLog, "CAP", 0)
    users, items, ts = random_stream(38, n=600, n_items=60, n_users=24)
    job = CooccurrenceJob(cfg(tmp_path, checkpoint_compact_ratio=1e9))
    feed(job, users, items, ts)
    job.finish()
    # Any touched row overflows the zero-capacity log, so every save
    # with actual churn behind it wrote a full base; a delta could land
    # only for a churn-free interval, and then it must be empty.
    d = str(tmp_path / "ckpt")
    for g in deltalog.delta_generations(d, ""):
        assert len(read_delta_file(deltalog.delta_path(d, "", g)).rows) \
            == 0


# -- the consumable delta log ------------------------------------------


def test_delta_stream_reader_and_topk_replay(chain_repo):
    """read_delta_stream yields the documented records in order, and
    replaying iter_topk over the base generation's table reproduces the
    final table — the replica catch-up contract."""
    d = chain_repo[1]
    top = ckpt.generations(d, "")[0][0]
    base, chain = ckpt.chain_of(d, "", top)
    assert chain, "no chain built"
    # Stream reader: ascending generations, start_gen exclusive.
    gens = [rec.gen for rec in read_delta_stream(d)]
    assert gens == sorted(gens) == chain
    assert [r.gen for r in read_delta_stream(d, start_gen=chain[0])] \
        == chain[1:]
    # Commit gate: an orphan delta (no generation npz — the shape a
    # crash between the two renames leaves) is never yielded; replaying
    # it would diverge a consumer when the writer rewrites it.
    import shutil

    orphan = deltalog.delta_path(d, "", top + 7)
    shutil.copyfile(deltalog.delta_path(d, "", chain[-1]), orphan)
    try:
        assert [r.gen for r in read_delta_stream(d)] == chain
    finally:
        os.remove(orphan)
    # Row records: cells and sums line up.
    rec = read_delta_file(deltalog.delta_path(d, "", chain[-1]))
    rows = list(rec.iter_rows())
    assert len(rows) == len(rec.rows)
    for r in rows[:5]:
        assert len(r["dsts"]) == len(r["cnts"])
        assert r["row_sum"] >= 0
    # Round trip through the codec is exact.
    rt = decode_delta(encode_delta(rec))
    np.testing.assert_array_equal(rt.cell_keys, rec.cell_keys)
    np.testing.assert_array_equal(rt.lat_scores, rec.lat_scores)
    # Replica simulation: base table + top-K replay == final table.
    bdata = ckpt._load_verified(os.path.join(d, f"state.{base}.npz"))
    table = {}
    items_b = bdata["latest_items"]
    off_b = bdata["latest_offsets"]
    for i, it in enumerate(items_b.tolist()):
        lo, hi = int(off_b[i]), int(off_b[i + 1])
        table[it] = list(zip(bdata["latest_others"][lo:hi].tolist(),
                             bdata["latest_scores"][lo:hi].tolist()))
    for drec in read_delta_stream(d):
        for t in drec.iter_topk():
            table[t["item"]] = t["top"]
    _, arrs = canonical_arrays(d)
    want = {}
    items_f = arrs["latest_items"]
    off_f = arrs["latest_offsets"]
    for i, it in enumerate(items_f.tolist()):
        lo, hi = int(off_f[i]), int(off_f[i + 1])
        want[it] = list(zip(arrs["latest_others"][lo:hi].tolist(),
                            arrs["latest_scores"][lo:hi].tolist()))
    assert table == want


def test_delta_file_rejects_tampering():
    z = np.zeros(0, dtype=np.int64)
    d = deltalog.DeltaGeneration(
        gen=3, prev=2, base=1, kind="sp", observed=10, row_sums_len=8,
        rows=np.asarray([1, 4], dtype=np.int64),
        row_sums=np.asarray([5, 5], dtype=np.int64),
        cell_lens=np.asarray([1, 1], dtype=np.int64),
        cell_keys=np.asarray([(1 << 32) | 2, (4 << 32) | 1],
                             dtype=np.int64),
        cell_cnts=np.asarray([5, 5], dtype=np.int64),
        lat_rows=np.asarray([7], dtype=np.int64),
        lat_lens=np.asarray([1], dtype=np.int64),
        lat_others=np.asarray([-3], dtype=np.int64),
        lat_scores=np.asarray([1.5], dtype=np.float64),
        usr_rows=np.asarray([2], dtype=np.int64),
        usr_lens=np.asarray([2], dtype=np.int64),
        usr_total=np.asarray([9], dtype=np.int64),
        usr_draws=np.asarray([4], dtype=np.int64),
        usr_hist=np.asarray([1, 4], dtype=np.int64),
        voc_items=np.asarray([100], dtype=np.int64),
        voc_users=z, hist_k=3, item_vocab_len=6, user_vocab_len=3)
    blob = encode_delta(d)
    rt = decode_delta(blob)
    assert rt.gen == 3 and rt.lat_others[0] == -3
    with pytest.raises(DeltaCorrupt):
        decode_delta(blob[:-10])
    bad = bytearray(blob)
    bad[20] ^= 0x01
    with pytest.raises(DeltaCorrupt):
        decode_delta(bytes(bad))


# -- observability -----------------------------------------------------


def test_journal_checkpoint_records(tmp_path):
    users, items, ts = random_stream(39, n=800, n_items=60, n_users=24)
    jpath = str(tmp_path / "journal.jsonl")
    job = CooccurrenceJob(cfg(tmp_path, journal=jpath,
                              checkpoint_compact_ratio=1e9))
    feed(job, users, items, ts)
    job.finish()
    recs = [json.loads(line) for line in open(jpath) if line.strip()]
    crecs = [r for r in recs if "checkpoint" in r]
    assert crecs, "no checkpoint record journaled"
    for r in crecs:
        validate_record(r)
        assert r["bytes"] > 0 and r["seconds"] >= 0
    kinds = {r["kind"] for r in crecs}
    assert kinds == {"full", "delta"}
    # Chain depth grows monotonically between compactions.
    deltas = [r for r in crecs if r["kind"] == "delta"]
    assert all(r["chain_len"] >= 1 for r in deltas)


def test_commit_gauges_and_healthz_fields(tmp_path):
    from tpu_cooccurrence.observability.registry import REGISTRY
    users, items, ts = random_stream(40, n=500, n_items=50, n_users=20)
    job = CooccurrenceJob(cfg(tmp_path))
    feed(job, users, items, ts)
    job.finish()
    assert REGISTRY.gauge(ckpt.COMMIT_BYTES_GAUGE).get() > 0
    assert REGISTRY.gauge(ckpt.COMMIT_SECONDS_GAUGE).get() >= 0
    from tpu_cooccurrence.observability.http import MetricsServer
    srv = MetricsServer(REGISTRY, port=0)
    payload, _healthy = srv.health()
    assert "checkpoint" in payload
    assert payload["checkpoint"]["generation"] >= 1
    assert payload["checkpoint"]["commit_bytes"] > 0
    srv._server.server_close()


# -- format-key registry (the ckpt-format-roundtrip rule's tests/
# reference: every meta / delta-header field is pinned HERE, so adding
# a writer-side field without updating reader + this list fails tier-1)


#: Generation-meta keys ``checkpoint.save`` writes (embedded meta_json).
#: ``gang_topology`` / ``rescaled_from`` are multi-host-only (the
#: autoscaler's rescale-tagged meta: the writing process layout, read
#: back by restore_rescaled's topology check and the rescale log line).
META_KEYS = {
    "seed", "skip_cuts", "item_cut", "user_cut", "top_k",
    "window_slide", "window_millis", "windows_fired", "emissions",
    "emissions_per_window_resume", "max_ts_seen", "counters",
    "source", "ckpt_codec", "ckpt_delta", "gang_topology",
    "rescaled_from", "ingest_offsets",
}

#: Delta-file header keys ``delta.encode_delta`` writes.
HEADER_KEYS = {
    "v", "gen", "prev", "base", "kind", "observed", "row_sums_len",
    "n_rows", "n_shards", "local_shards", "hist_k", "item_vocab_len",
    "user_vocab_len", "payload", "sections", "ingest_offsets",
}


def test_checkpoint_format_keys_pinned(chain_repo):
    """The on-disk format registry: a checkpoint's embedded meta and a
    delta file's header hold exactly the pinned key sets (``source`` and
    the two codec records are conditional). Growing either format means
    updating this test — which is the rule's point."""
    d = chain_repo[1]
    gen, path = ckpt.generations(d, "")[0]
    data = ckpt._load_verified(path)
    meta = json.loads(bytes(data["meta_json"]).decode())
    optional = {"source", "ckpt_codec", "ckpt_delta", "gang_topology",
                "rescaled_from", "ingest_offsets"}
    assert META_KEYS - optional <= set(meta) <= META_KEYS
    rec = read_delta_file(
        deltalog.delta_path(d, "", deltalog.delta_generations(d, "")[-1]))
    blob = encode_delta(rec)
    hlen = int(np.frombuffer(blob[8:12], dtype=np.uint32)[0])
    header = json.loads(blob[12:12 + hlen].decode("ascii"))
    assert set(header) == HEADER_KEYS


# -- config gating -----------------------------------------------------


def test_incremental_config_gating(tmp_path):
    with pytest.raises(ValueError, match="sparse-family"):
        Config(window_size=10, backend=Backend.DEVICE,
               checkpoint_incremental=True)
    with pytest.raises(ValueError, match="breaker"):
        Config(window_size=10, backend=Backend.SPARSE,
               checkpoint_incremental=True, scorer_breaker_threshold=2)
    with pytest.raises(ValueError, match="compact-ratio"):
        Config(window_size=10, checkpoint_compact_ratio=0.0)
    # Sharded-sparse accepts it (the mh chain path).
    Config(window_size=10, backend=Backend.SPARSE, num_shards=2,
           checkpoint_incremental=True)
