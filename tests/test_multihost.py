"""Real multi-host execution: 2 coordinated processes on the CPU backend.

Each subprocess joins the multi-controller runtime through
``jax.distributed.initialize`` (via ``--coordinator``/``--num-processes``/
``--process-id``), gets 4 virtual local devices, and runs the sharded
backend over the resulting 8-device global mesh. This exercises the real
multi-host code paths — ``init_multihost``, ``make_multihost_mesh`` (DCN-
aware hosts-major device order), ``put_global``'s per-shard callback
assembly, addressable-shard result extraction, and per-process
checkpoints — none of which single-process tests can reach.

The in-process reference is the same stream on a single-process 8-shard
virtual mesh (the conftest's), whose results the two processes' merged,
disjoint row partitions must reproduce exactly.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

from tpu_cooccurrence.config import Backend, Config

from test_pipeline import random_stream, run_production

# Two-process coordinated runs: minutes of wall-clock. Slow lane
# (deselected by default; TPU_COOC_FULL_SUITE=1 selects it back in).
pytestmark = pytest.mark.slow

WORKER = os.path.join(os.path.dirname(__file__), "multihost_worker.py")

STREAM_KW = dict(window_size=10, seed=0x51AB, item_cut=6, user_cut=4,
                 num_items=32)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn_procs(tmp_path, phase: str, half: int, stream_path: str,
                 checkpoint_dir: str, backend: str = "sharded",
                 partition_sampling: bool = False,
                 window_slide: int = None, nproc: int = 2,
                 expect_failure: bool = False, pipeline_depth: int = 0):
    """Launch all ``nproc`` processes of one phase; return parsed outputs
    (or, with ``expect_failure``, the list of (rc, stderr) per process).

    The global mesh is always 8 devices: each process gets ``8 // nproc``
    virtual local devices, so 2- and 4-process runs shard the same state
    over the same mesh size with different host boundaries."""
    assert 8 % nproc == 0
    coordinator = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={8 // nproc}")
    env["PALLAS_AXON_POOL_IPS"] = ""  # skip any accelerator plugin probe
    # `python path/to/worker.py` puts tests/ on sys.path, not the repo root.
    repo_root = os.path.dirname(os.path.dirname(WORKER))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    procs, outs = [], []
    for pid in range(nproc):
        spec = dict(STREAM_KW, stream=stream_path, coordinator=coordinator,
                    num_processes=nproc, process_id=pid, phase=phase,
                    half=half, checkpoint_dir=checkpoint_dir,
                    backend=backend, num_shards=8,
                    partition_sampling=partition_sampling,
                    window_slide=window_slide,
                    pipeline_depth=pipeline_depth)
        tag = (f"{backend}{'-ps' if partition_sampling else ''}"
               f"{'-sl' if window_slide else ''}"
               f"{f'-d{pipeline_depth}' if pipeline_depth else ''}"
               f"-n{nproc}")
        spec_path = tmp_path / f"spec-{tag}-{phase}-{pid}.json"
        out_path = tmp_path / f"out-{tag}-{phase}-{pid}.json"
        spec_path.write_text(json.dumps(spec))
        outs.append(out_path)
        procs.append(subprocess.Popen(
            [sys.executable, WORKER, str(spec_path), str(out_path)],
            env=env, cwd=os.path.dirname(os.path.dirname(WORKER)),
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    results, failures = [], []
    for p, out_path in zip(procs, outs):
        stdout, stderr = p.communicate(timeout=300)
        if expect_failure:
            failures.append((p.returncode, stderr))
            continue
        assert p.returncode == 0, f"worker failed:\n{stdout}\n{stderr}"
        results.append(json.loads(out_path.read_text()))
    return failures if expect_failure else results


def _spawn_pair(tmp_path, phase, half, stream_path, checkpoint_dir,
                backend="sharded", partition_sampling=False,
                window_slide=None):
    return _spawn_procs(tmp_path, phase, half, stream_path, checkpoint_dir,
                        backend=backend,
                        partition_sampling=partition_sampling,
                        window_slide=window_slide, nproc=2)


def _merge_latest(results):
    merged = {}
    for res in results:
        for item, top in res["latest"].items():
            assert item not in merged, \
                f"row {item} emitted by more than one process"
            merged[int(item)] = [(int(j), s) for j, s in top]
    return merged


def _reference_latest(users, items, ts, backend: str = "sharded",
                      window_slide: int = None):
    cfg = Config(**STREAM_KW, backend=Backend(backend), num_shards=8,
                 window_slide=window_slide)
    job = run_production(cfg, users, items, ts)
    return ({item: job.latest[item] for item in job.latest},
            job.counters.as_dict())


@pytest.fixture(scope="module")
def stream(tmp_path_factory):
    path = tmp_path_factory.mktemp("mh") / "stream.npz"
    users, items, ts = random_stream(61, n=500)
    np.savez(path, users=users, items=items, ts=ts)
    return str(path), users, items, ts


def _assert_matches_reference(results, users, items, ts,
                              backend: str = "sharded",
                              window_slide: int = None):
    ref_latest, ref_counters = _reference_latest(users, items, ts, backend,
                                                 window_slide)
    merged = _merge_latest(results)
    assert set(merged) == set(ref_latest)
    for item in ref_latest:
        r = ref_latest[item]
        m = merged[item]
        np.testing.assert_allclose([s for _, s in m], [s for _, s in r],
                                   rtol=1e-6, atol=1e-6)
        # Tie-aware id comparison: the sparse backend breaks equal scores
        # by slab slot order, which a checkpoint restore re-lays (sorted
        # key order) — ids must match as sets within each tie group.
        rv = np.asarray([s for _, s in r])
        lo = 0
        for hi in range(1, len(rv) + 1):
            if hi == len(rv) or not np.isclose(rv[hi], rv[lo], rtol=1e-6):
                assert ({j for j, _ in r[lo:hi]}
                        == {j for j, _ in m[lo:hi]}), f"row {item}"
                lo = hi
    # Host-side pipeline state is identical in every process (each consumes
    # the whole stream), so the counters must match the single-process run.
    for res in results:
        assert res["counters"] == ref_counters


def test_multihost_two_processes_match_single_process(tmp_path, stream):
    stream_path, users, items, ts = stream
    results = _spawn_pair(tmp_path, "full", len(users), stream_path,
                          checkpoint_dir=None)
    _assert_matches_reference(results, users, items, ts)


def test_multihost_per_process_checkpoint_resume(tmp_path, stream):
    stream_path, users, items, ts = stream
    ck_dir = str(tmp_path / "ck")
    half = 250
    _spawn_pair(tmp_path, "first-half", half, stream_path, ck_dir)
    # Both per-process snapshots must exist (hosts-major row blocks;
    # generation-numbered since the robustness PR).
    import glob as _glob

    assert _glob.glob(os.path.join(ck_dir, "state.p0.*.npz"))
    assert _glob.glob(os.path.join(ck_dir, "state.p1.*.npz"))
    results = _spawn_pair(tmp_path, "resume", half, stream_path, ck_dir)
    _assert_matches_reference(results, users, items, ts)


def test_multihost_sharded_sparse_matches_single_process(tmp_path, stream):
    """The row-sharded HBM-slab backend runs multi-controller too: same
    merged results and counters as a single-process 8-shard mesh."""
    stream_path, users, items, ts = stream
    results = _spawn_pair(tmp_path, "full", len(users), stream_path,
                          checkpoint_dir=None, backend="sparse")
    _assert_matches_reference(results, users, items, ts, backend="sparse")


def test_multihost_sharded_sparse_checkpoint_resume(tmp_path, stream):
    stream_path, users, items, ts = stream
    ck_dir = str(tmp_path / "ck-sparse")
    half = 250
    _spawn_pair(tmp_path, "first-half", half, stream_path, ck_dir,
                backend="sparse")
    assert os.path.exists(os.path.join(ck_dir, "state.p0.npz"))
    assert os.path.exists(os.path.join(ck_dir, "state.p1.npz"))
    results = _spawn_pair(tmp_path, "resume", half, stream_path, ck_dir,
                          backend="sparse")
    _assert_matches_reference(results, users, items, ts, backend="sparse")


def test_multihost_partitioned_sampling_matches_replicated(tmp_path, stream):
    """--partition-sampling: each process reservoirs 1/P of the users and
    the per-window allgather reproduces the serial pipeline exactly —
    results AND counters (the RNG is partition-independent by design)."""
    stream_path, users, items, ts = stream
    results = _spawn_pair(tmp_path, "full", len(users), stream_path,
                          checkpoint_dir=None, partition_sampling=True)
    _assert_matches_reference(results, users, items, ts)


def test_multihost_partitioned_sampling_checkpoint_resume(tmp_path, stream):
    stream_path, users, items, ts = stream
    ck_dir = str(tmp_path / "ck-ps")
    half = 250
    _spawn_pair(tmp_path, "first-half", half, stream_path, ck_dir,
                partition_sampling=True)
    results = _spawn_pair(tmp_path, "resume", half, stream_path, ck_dir,
                          partition_sampling=True)
    _assert_matches_reference(results, users, items, ts)


def test_multihost_sparse_with_partitioned_sampling(tmp_path, stream):
    """Both scale axes at once: row-sharded HBM slabs across hosts AND the
    user reservoir partitioned across the same processes."""
    stream_path, users, items, ts = stream
    results = _spawn_pair(tmp_path, "full", len(users), stream_path,
                          checkpoint_dir=None, backend="sparse",
                          partition_sampling=True)
    _assert_matches_reference(results, users, items, ts, backend="sparse")


def test_multihost_four_processes_sharded(tmp_path, stream):
    """4 coordinated processes x 2 local devices = the same 8-device mesh
    with host boundaries every 2 shards; merged results and counters must
    still match the single-process reference."""
    stream_path, users, items, ts = stream
    results = _spawn_procs(tmp_path, "full", len(users), stream_path,
                           checkpoint_dir=None, nproc=4)
    _assert_matches_reference(results, users, items, ts)


def test_multihost_four_processes_sharded_sparse_with_ps(tmp_path, stream):
    """Both scale axes at 4 processes: row-sharded HBM slabs AND the
    user reservoir partitioned 4 ways."""
    stream_path, users, items, ts = stream
    results = _spawn_procs(tmp_path, "full", len(users), stream_path,
                           checkpoint_dir=None, backend="sparse",
                           partition_sampling=True, nproc=4)
    _assert_matches_reference(results, users, items, ts, backend="sparse")


def test_multihost_four_process_checkpoint_resume(tmp_path, stream):
    stream_path, users, items, ts = stream
    ck_dir = str(tmp_path / "ck-n4")
    half = 250
    _spawn_procs(tmp_path, "first-half", half, stream_path, ck_dir, nproc=4)
    for pid in range(4):
        assert os.path.exists(os.path.join(ck_dir, f"state.p{pid}.npz"))
    results = _spawn_procs(tmp_path, "resume", half, stream_path, ck_dir,
                           nproc=4)
    _assert_matches_reference(results, users, items, ts)


def test_multihost_layout_mismatch_restore_fails(tmp_path, stream):
    """A checkpoint written by a 2-process run must REFUSE to restore
    under a 4-process layout (both backends validate; garbage slices
    would otherwise corrupt state silently)."""
    stream_path, users, items, ts = stream
    ck_dir = str(tmp_path / "ck-mismatch")
    half = 250
    _spawn_pair(tmp_path, "first-half", half, stream_path, ck_dir)
    failures = _spawn_procs(tmp_path, "resume", half, stream_path, ck_dir,
                            nproc=4, expect_failure=True)
    # p2/p3 find no state.p{2,3}.npz; p0/p1 find blocks for the wrong row
    # span. Every process must fail, none silently.
    assert all(rc != 0 for rc, _ in failures)
    assert any("layout" in err or "checkpoint" in err
               for _, err in failures)


def test_multihost_partitioned_sampling_layout_mismatch_fails(tmp_path,
                                                              stream):
    """--partition-sampling checkpoints record their (pid, nproc); a
    4-process resume of a 2-process snapshot fails with the layout
    error, not silent reservoir corruption."""
    stream_path, users, items, ts = stream
    ck_dir = str(tmp_path / "ck-ps-mismatch")
    half = 250
    _spawn_pair(tmp_path, "first-half", half, stream_path, ck_dir,
                partition_sampling=True)
    failures = _spawn_procs(tmp_path, "resume", half, stream_path, ck_dir,
                            nproc=4, partition_sampling=True,
                            expect_failure=True)
    assert all(rc != 0 for rc, _ in failures)


def test_multihost_partitioned_sliding_matches_replicated(tmp_path, stream):
    """Sliding mode under --partition-sampling: replicated cuts, user-
    partitioned basket expansion, packed allgather — same results and
    counters as the single-process sliding run."""
    stream_path, users, items, ts = stream
    results = _spawn_pair(tmp_path, "full", len(users), stream_path,
                          checkpoint_dir=None, partition_sampling=True,
                          window_slide=5)
    _assert_matches_reference(results, users, items, ts, window_slide=5)


def test_multihost_pipelined_depth2_matches_single_process(tmp_path,
                                                           stream):
    """ISSUE 10 relaxed the blanket multi-host pipeline rejection:
    without --partition-sampling every collective issues from the
    scorer worker in window order, so a depth-2 two-process run must
    reproduce the single-process serial reference exactly."""
    stream_path, users, items, ts = stream
    results = _spawn_procs(tmp_path, "full", len(users), stream_path,
                           checkpoint_dir=None, nproc=2,
                           pipeline_depth=2)
    _assert_matches_reference(results, users, items, ts)
