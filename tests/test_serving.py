"""Serving plane: snapshots, the /recommend blend, and the query storm.

Pins the PR-8 contracts:

* snapshot correctness — the published table always matches
  ``LatestResults`` row for row, through compaction and re-publication;
* the double-buffer swap protocol — readers hammering ``/recommend``
  during live window swaps (pipeline depths 0 and 2) never observe a
  torn table: every response is internally consistent against exactly
  one snapshot generation;
* the hot-path contract — no lock acquisition, no per-query table
  allocation (test instrumentation: a spying lock on ``LatestResults``
  plus the ``SCRATCH_ALLOCATIONS`` counter);
* parity — serving enabled vs disabled leaves ingest output
  bit-identical at depths 0 and 2;
* degradation — under a query storm plus ingest overload the controller
  sheds INGEST (SHED_SAMPLING/SHED_K) while query p99 stays bounded,
  with transitions journaled;
* ``/healthz`` — snapshot generation/staleness, 503 past
  ``--serve-stale-after-s``.
"""

import json
import threading
import time
import urllib.parse
from urllib.request import urlopen

import numpy as np
import pytest

from tpu_cooccurrence.config import Backend, Config
from tpu_cooccurrence.job import CooccurrenceJob
from tpu_cooccurrence.observability import LEDGER
from tpu_cooccurrence.observability.http import MetricsServer
from tpu_cooccurrence.observability.journal import (
    read_records,
    validate_record,
)
from tpu_cooccurrence.observability.registry import REGISTRY
from tpu_cooccurrence.serving import recommend as recommend_mod
from tpu_cooccurrence.serving.snapshot import SnapshotBuilder
from tpu_cooccurrence.serving.recommend import ServingPlane, UserHistory
from tpu_cooccurrence.state.results import LatestResults, TopKBatch


@pytest.fixture(autouse=True)
def _reset_registries():
    REGISTRY.reset()
    LEDGER.reset()
    yield
    from tpu_cooccurrence.robustness import degrade

    degrade.uninstall()


def _stream(seed, n=9_000, n_users=150, n_items=400):
    rng = np.random.default_rng(seed)
    users = rng.integers(0, n_users, n).astype(np.int64)
    items = rng.integers(0, n_items, n).astype(np.int64)
    ts = np.cumsum(rng.integers(0, 2, n)).astype(np.int64)
    return users, items, ts


def _cfg(**over):
    kw = dict(window_size=50, seed=5, item_cut=50, user_cut=20,
              backend=Backend.ORACLE)
    kw.update(over)
    return Config(**kw)


def _run(cfg, users, items, ts):
    job = CooccurrenceJob(cfg)
    job.add_batch(users, items, ts)
    job.finish()
    return job


# ---------------------------------------------------------------------------
# snapshot builder / lookup correctness


def test_snapshot_matches_latest_results():
    users, items, ts = _stream(3)
    job = _run(_cfg(serve_port=0), users, items, ts)
    snap = job.serving.builder.current
    latest = job.latest.snapshot()
    assert snap.rows == len(latest)
    ext_of = job.item_vocab.external_array()
    for ext in latest:
        dense = job.item_vocab.to_dense(ext)
        row = snap.row(dense)
        assert row is not None
        idx, vals = row
        expect = latest[ext]
        got = list(zip(ext_of[idx.astype(np.int64)].tolist(),
                       vals.astype(float).tolist()))
        # Items and order exact; scores float32-rounded at the packed
        # boundary (host-backend rows store float64).
        assert [i for i, _ in got] == [i for i, _ in expect]
        assert [s for _, s in got] == pytest.approx(
            [s for _, s in expect], rel=1e-6)
    # Items never emitted are absent, in and beyond the bitmap extent.
    assert snap.row(len(job.item_vocab) + 5) is None
    assert snap.row(10 ** 9) is None
    assert snap.row(-1) is None


def test_builder_incremental_and_compaction():
    vocab_stub = _VocabStub(64)
    b = SnapshotBuilder(vocab_stub)
    b._COMPACT_MIN_ROWS = 8  # force compaction in-test
    rng = np.random.default_rng(0)
    latest = {}
    for w in range(30):
        rows = rng.choice(64, size=4, replace=False).astype(np.int32)
        idx = rng.integers(0, 64, (4, 3)).astype(np.int32)
        vals = -np.sort(-rng.random((4, 3)).astype(np.float32), axis=1)
        vals[:, 2] = -np.inf  # short rows exercise the lens precompute
        b.absorb(TopKBatch(rows, idx, vals))
        for r in range(4):
            latest[int(rows[r])] = (idx[r, :2].tolist(),
                                    vals[r, :2].tolist())
        snap = b.publish()
        assert snap.generation == w + 1
    assert snap.rows == len(latest)
    for item, (want_idx, want_vals) in latest.items():
        got_idx, got_vals = snap.row(item)
        assert got_idx.tolist() == want_idx
        assert got_vals.tolist() == pytest.approx(want_vals)
    assert len(b._segments) < 30  # compaction actually folded segments


def test_quiet_boundary_keeps_object_but_advances_swap_clock():
    vocab_stub = _VocabStub(8)
    b = SnapshotBuilder(vocab_stub)
    b.absorb(TopKBatch(np.array([1], np.int32),
                       np.array([[2]], np.int32),
                       np.array([[1.0]], np.float32)))
    s1 = b.publish()
    swaps = b.swaps
    clock = b.last_swap_unix
    time.sleep(0.005)
    s2 = b.publish()  # nothing absorbed in between: quiet boundary
    assert s2 is s1  # content generation unchanged, object kept
    assert b.swaps == swaps + 1  # but the swap clock advanced
    assert b.last_swap_unix > clock


def test_double_buffer_recycles_only_unreferenced_snapshots():
    vocab_stub = _VocabStub(128)
    b = SnapshotBuilder(vocab_stub)

    def absorb(w):
        # Same row id every window: the live set (and so the packed
        # capacities) stays constant — the recycling steady state.
        b.absorb(TopKBatch(np.array([5], np.int32),
                           np.array([[w + 1]], np.int32),
                           np.array([[1.0]], np.float32)))

    absorb(0)
    g1 = b.publish()
    g1_bits = g1.bits
    absorb(1)
    b.publish()
    del g1  # no reader holds gen 1 -> its arrays are recyclable
    absorb(2)
    g3 = b.publish()
    assert np.shares_memory(g3.bits, g1_bits)  # the double buffer
    # A straggling reader keeps its generation intact: hold gen 3 and
    # publish twice more — gen 3's content must not change underneath.
    held_bits = g3.bits.copy()
    held_seg = g3.seg_of.copy()
    absorb(3)
    b.publish()
    absorb(4)
    b.publish()
    assert np.array_equal(g3.bits, held_bits)
    assert np.array_equal(g3.seg_of, held_seg)


class _VocabStub:
    """Fixed-size identity vocab for builder unit tests."""

    def __init__(self, n: int) -> None:
        self._n = n

    def __len__(self) -> int:
        return self._n

    def external_array(self) -> np.ndarray:
        return np.arange(self._n, dtype=np.int64)


# ---------------------------------------------------------------------------
# user history + blend


def test_user_history_ring_bounds_and_wraps():
    h = UserHistory(length=4)
    h.extend(np.array([7, 7, 7]), np.array([1, 2, 3]))
    out = np.zeros(4, dtype=np.int64)
    assert h.recent(7, out) == 3
    assert sorted(out[:3].tolist()) == [1, 2, 3]
    h.extend(np.array([7, 7, 7]), np.array([4, 5, 6]))
    assert h.recent(7, out) == 4  # bounded at the ring length
    assert set(out.tolist()) <= {1, 2, 3, 4, 5, 6}
    assert h.recent(99, out) == 0  # unseen user
    # Vectorized multi-user batch lands per user in stream order.
    h2 = UserHistory(length=8)
    h2.extend(np.array([1, 2, 1, 2, 1]), np.array([10, 20, 11, 21, 12]))
    assert h2.recent(1, out[:8]) == 3 and out[:3].tolist() == [10, 11, 12]


def test_query_blends_history_filters_seen_and_falls_back():
    users, items, ts = _stream(4)
    job = _run(_cfg(serve_port=0), users, items, ts)
    plane = job.serving
    u = int(users[0])
    got, snap, fallback = plane.query(u, 5)
    assert not fallback and 0 < len(got) <= 5
    scores = [s for _, s in got]
    assert scores == sorted(scores, reverse=True)
    assert len({i for i, _ in got}) == len(got)  # no duplicates
    # Already-seen filtering: nothing in the user's history is returned.
    dense_u = job.user_vocab.to_dense(u)
    hist = np.zeros(plane.history.length, dtype=np.int64)
    k = plane.history.recent(dense_u, hist)
    seen_ext = {int(job.item_vocab.external_array()[d])
                for d in hist[:k]}
    assert not seen_ext & {i for i, _ in got}
    # The blend is the history x rows sum: recompute independently. Ask
    # for every candidate (big n) so near-tie ordering at a cut boundary
    # cannot flake the comparison; scores float32-accumulated vs this
    # float64 oracle.
    got_all, _, _ = plane.query(u, 900)
    latest = job.latest.snapshot()
    acc = {}
    for d in hist[:k]:
        ext = int(job.item_vocab.external_array()[d])
        if ext not in latest:
            continue
        for other, s in latest[ext]:
            acc[other] = acc.get(other, 0.0) + s
    for ext_seen in seen_ext:
        acc.pop(ext_seen, None)
    assert {i for i, _ in got_all} == set(acc)
    for gi, gs in got_all:
        assert gs == pytest.approx(acc[gi], rel=1e-4)
    # Anonymous and unknown users take the popularity fallback.
    anon, _, fb = plane.query(None, 3)
    assert fb and len(anon) == 3
    cold, _, fb2 = plane.query(10 ** 12, 3)
    assert fb2 and [i for i, _ in cold] == [i for i, _ in anon]
    pop_scores = [s for _, s in anon]
    assert pop_scores == sorted(pop_scores, reverse=True)


def test_query_n_clamped_and_empty_snapshot_safe():
    job = CooccurrenceJob(_cfg(serve_port=0))
    got, snap, fallback = job.serving.query(None, 10)
    assert got == [] and fallback and snap.generation == 0
    users, items, ts = _stream(5, n=5000)
    job.add_batch(users, items, ts)
    job.finish()
    got, _, _ = job.serving.query(None, 10 ** 9)  # clamped, not O(vocab)
    assert len(got) <= recommend_mod.MAX_N


# ---------------------------------------------------------------------------
# hot-path contract: no locks, no per-query table allocation


class _SpyLock:
    """Counting wrapper around an RLock (test instrumentation)."""

    def __init__(self, inner):
        self._inner = inner
        self.acquires = 0

    def __enter__(self):
        self.acquires += 1
        return self._inner.__enter__()

    def __exit__(self, *exc):
        return self._inner.__exit__(*exc)

    def acquire(self, *a, **kw):
        self.acquires += 1
        return self._inner.acquire(*a, **kw)

    def release(self):
        return self._inner.release()


def test_query_path_acquires_no_lock_and_reuses_scratch():
    users, items, ts = _stream(6)
    job = _run(_cfg(serve_port=0), users, items, ts)
    plane = job.serving
    spy = _SpyLock(job.latest._lock)
    job.latest._lock = spy
    # The snapshot classes hold no lock at all, by construction.
    assert not hasattr(plane.builder.current, "_lock")
    assert not hasattr(plane.builder, "_lock")
    assert not hasattr(plane.history, "_lock")
    # Warm the per-thread scratch, then pin the steady state.
    plane.query(int(users[0]), 10)
    plane.query(None, 10)
    snap_before = plane.builder.current
    arrays_before = (id(snap_before.bits), id(snap_before.seg_of))
    allocs_before = recommend_mod.SCRATCH_ALLOCATIONS
    base_acquires = spy.acquires
    rng = np.random.default_rng(0)
    for _ in range(300):
        plane.query(int(rng.integers(0, 200)), 10)
    assert spy.acquires == base_acquires  # zero lock acquisitions
    assert recommend_mod.SCRATCH_ALLOCATIONS == allocs_before
    assert plane.builder.current is snap_before  # and no hidden swap
    assert (id(snap_before.bits), id(snap_before.seg_of)) == arrays_before
    # Sanity: the spy does count — a LatestResults read takes the lock.
    _ = job.latest[next(iter(job.latest.snapshot()))]
    assert spy.acquires > base_acquires


# ---------------------------------------------------------------------------
# parity: serving on vs off is bit-identical on ingest output


@pytest.mark.parametrize("depth", [0, 2])
def test_serving_parity_bit_identical(depth):
    users, items, ts = _stream(7)
    kw = dict(pipeline_depth=depth, development_mode=True)
    off = _run(_cfg(**kw), users, items, ts)
    REGISTRY.reset()
    on = _run(_cfg(serve_port=0, **kw), users, items, ts)
    a = {k: v for k, v in off.latest.snapshot().items()}
    b = {k: v for k, v in on.latest.snapshot().items()}
    assert a == b
    assert off.counters.as_dict() == on.counters.as_dict()


# ---------------------------------------------------------------------------
# concurrent reader/writer: /recommend hammered during live window swaps


def _window_aligned_stream(seed, n_chunks, per_chunk, window_ms,
                           n_users=120, n_items=300):
    """One chunk per window: chunk c's timestamps live in window c, so
    every add_batch(chunk) fires exactly the previous window."""
    rng = np.random.default_rng(seed)
    users, items, ts = [], [], []
    for c in range(n_chunks):
        users.append(rng.integers(0, n_users, per_chunk).astype(np.int64))
        items.append(rng.integers(0, n_items, per_chunk).astype(np.int64))
        t0 = c * window_ms
        ts.append(np.sort(rng.integers(
            t0, t0 + window_ms, per_chunk)).astype(np.int64))
    return users, items, ts


@pytest.mark.parametrize("depth", [0, 2])
def test_recommend_hammer_during_live_swaps(depth):
    """Zero torn reads: every /recommend response during live swaps is
    internally consistent (unique items, descending scores) and carries
    exactly one snapshot generation; generations advance while the storm
    runs, proving the swaps were live."""
    cfg = _cfg(serve_port=0, pipeline_depth=depth)
    job = CooccurrenceJob(cfg)
    srv = MetricsServer(REGISTRY, counters=job.counters, ledger=LEDGER,
                        port=0, serving=job.serving).start()
    users, items, ts = _window_aligned_stream(8 + depth, n_chunks=24,
                                              per_chunk=500, window_ms=50)
    stop = threading.Event()
    results = []
    errors = []

    def storm(tid):
        rng = np.random.default_rng(tid)
        while not stop.is_set():
            u = int(rng.integers(0, 120))
            try:
                with urlopen(
                        f"http://127.0.0.1:{srv.port}/recommend"
                        f"?user={u}&n=8", timeout=10) as r:
                    results.append(json.loads(r.read().decode()))
            except Exception as exc:  # torn read, bad JSON, 5xx ...
                errors.append(repr(exc))

    threads = [threading.Thread(target=storm, args=(t,), daemon=True)
               for t in range(4)]
    for t in threads:
        t.start()
    try:
        for u, i, tt in zip(users, items, ts):
            job.add_batch(u, i, tt)
        job.finish()
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)
        srv.stop()
    assert not errors, errors[:3]
    assert len(results) > 50
    gens = set()
    for body in results:
        gens.add(body["generation"])
        seen_items = [it["item"] for it in body["items"]]
        scores = [it["score"] for it in body["items"]]
        assert len(set(seen_items)) == len(seen_items)
        assert scores == sorted(scores, reverse=True)
        assert isinstance(body["fallback"], bool)
    assert len(gens) > 1  # the storm really overlapped live swaps
    assert job.serving.generation == max(gens) or \
        job.serving.generation >= max(gens)


# ---------------------------------------------------------------------------
# degradation: a query storm + ingest overload sheds INGEST, not queries


def test_query_storm_sheds_ingest_while_query_p99_bounded(tmp_path):
    jpath = str(tmp_path / "journal.jsonl")
    cfg = _cfg(serve_port=0, degrade=True, journal=jpath,
               serve_query_slo_s=1e-9,  # every query over-SLO: storm proxy
               degrade_trip_windows=2, degrade_clear_windows=99,
               degrade_window_wall_s=60.0)  # wall never trips: only
    # QUERY_PRESSURE drives the ladder in this test
    job = CooccurrenceJob(cfg)
    srv = MetricsServer(REGISTRY, counters=job.counters, ledger=LEDGER,
                        port=0, serving=job.serving).start()
    users, items, ts = _window_aligned_stream(11, n_chunks=12,
                                              per_chunk=500, window_ms=50)
    latencies = []
    try:
        for u, i, tt in zip(users, items, ts):
            for _ in range(3):
                t0 = time.perf_counter()
                with urlopen(f"http://127.0.0.1:{srv.port}/recommend"
                             f"?user={int(u[0])}&n=5", timeout=10) as r:
                    r.read()
                latencies.append(time.perf_counter() - t0)
            job.add_batch(u, i, tt)
        level = int(job.degrade.level)
        job.finish()
    finally:
        srv.stop()
    from tpu_cooccurrence.robustness.degrade import DegradationLevel

    # Ingest was shed: the ladder climbed at least into SHED_K, and the
    # effective cuts tightened (the paper's own shedding lever).
    assert level >= DegradationLevel.SHED_K
    # Queries were NOT shed: every one was answered, tail bounded.
    assert len(latencies) == 12 * 3
    assert float(np.percentile(latencies, 99)) < 1.0
    # Transitions are journaled.
    events = []
    for rec in read_records(jpath):
        validate_record(rec)
        events.extend(rec.get("degrade_events", []))
        if "event" in rec:
            events.append(rec["event"])
    assert "degrade/enter_shed_sampling" in events
    assert "degrade/enter_shed_k" in events
    # QUERY_PRESSURE is visible on the registry.
    assert REGISTRY.gauge("cooc_query_pressure_events_total").get() > 0


def test_note_query_pressure_marks_next_window_overloaded():
    from tpu_cooccurrence.robustness.degrade import (
        DegradationController,
        DegradationLevel,
    )

    c = DegradationController(window_wall_s=10.0, trip_windows=2,
                              clear_windows=8)
    for _ in range(2):
        c.note_query_pressure()
        c.observe_window(wall_seconds=0.001)
    assert c.level == DegradationLevel.SHED_SAMPLING
    # Without the signal the same fast windows are healthy.
    c2 = DegradationController(window_wall_s=10.0, trip_windows=2,
                               clear_windows=8)
    for _ in range(4):
        c2.observe_window(wall_seconds=0.001)
    assert c2.level == DegradationLevel.NORMAL


# ---------------------------------------------------------------------------
# journal + healthz + restore


def test_journal_carries_snapshot_generation(tmp_path):
    jpath = str(tmp_path / "j.jsonl")
    users, items, ts = _stream(9, n=8000)
    job = _run(_cfg(serve_port=0, journal=jpath), users, items, ts)
    recs = [r for r in read_records(jpath) if "event" not in r]
    assert recs
    for r in recs:
        validate_record(r)
        assert "snapshot_generation" in r and "snapshot_rows" in r
    gens = [r["snapshot_generation"] for r in recs]
    assert gens == sorted(gens)  # swap counter is monotone
    assert job.serving.generation > gens[-1] - 1


def test_healthz_reports_snapshot_and_503_when_stale():
    users, items, ts = _stream(10, n=6000)
    job = _run(_cfg(serve_port=0), users, items, ts)
    srv = MetricsServer(REGISTRY, counters=job.counters, ledger=LEDGER,
                        port=0, serving=job.serving,
                        serve_stale_after_s=0.0)
    try:
        payload, healthy = srv.health()
        assert healthy
        assert payload["snapshot_generation"] == job.serving.generation
        assert payload["snapshot_rows"] == job.serving.rows
        assert payload["snapshot_age_seconds"] >= 0
        # Default off: an old snapshot alone never 503s.
        srv.serve_stale_after_s = 0.0
        job.serving.builder.current.__class__  # (no-op; readability)
        # Arm the drain signal and age the snapshot past it.
        srv.serve_stale_after_s = 0.001
        time.sleep(0.01)
        payload, healthy = srv.health()
        assert not healthy and payload["status"] == "snapshot_stale"
    finally:
        srv.stop()


def test_restore_seeds_serving_snapshot(tmp_path):
    users, items, ts = _stream(12, n=8000)
    cfg = _cfg(serve_port=0, checkpoint_dir=str(tmp_path / "ckpt"))
    job = CooccurrenceJob(cfg)
    job.add_batch(users, items, ts)
    job.finish()
    job.checkpoint()
    rows_then = len(job.latest.snapshot())
    REGISTRY.reset()
    job2 = CooccurrenceJob(cfg)
    job2.restore()
    # A resumed job serves its checkpointed rows before any new window.
    assert job2.serving.rows == rows_then > 0
    got, snap, fallback = job2.serving.query(None, 5)
    assert len(got) == 5 and fallback


# ---------------------------------------------------------------------------
# results snapshot (satellite): copy-under-lock consistency


def test_latest_results_snapshot_is_consistent_copy():
    users, items, ts = _stream(13, n=6000)
    job = _run(_cfg(), users, items, ts)
    snap = job.latest.snapshot()
    before = {k: v for k, v in snap.items()}
    # Mutate the live store after the copy: the snapshot must not move.
    job.latest.set_row(0, [(1, 9.9)])
    job.latest.absorb_batch(TopKBatch(
        np.array([2], np.int32), np.array([[3]], np.int32),
        np.array([[8.8]], np.float32)))
    assert {k: v for k, v in snap.items()} == before
    assert len(snap) == len(before)
    ext0 = job.item_vocab.to_external(0)
    assert job.latest[ext0] == [(job.item_vocab.to_external(1), 9.9)]
    # packed() round-trips the live rows (dense ids, finite-filtered).
    packed = snap.packed()
    assert len(packed) == len(before)
    from tpu_cooccurrence.state.results import materialize_dense

    ext_arr = job.item_vocab.external_array()
    for dense_item, top in materialize_dense(packed):
        ext = int(ext_arr[dense_item])
        want = before[ext]
        got = [(int(ext_arr[j]), s) for j, s in top]
        assert [i for i, _ in got] == [i for i, _ in want]
        assert [s for _, s in got] == pytest.approx(
            [s for _, s in want], rel=1e-6)


def test_results_snapshot_packs_list_and_array_batches():
    class _Vocab:
        def __init__(self):
            self._rev = list(range(100))

        def __len__(self):
            return 100

        def to_dense(self, e):
            return e if 0 <= e < 100 else None

        def to_external(self, d):
            return d

        def external_array(self):
            return np.arange(100, dtype=np.int64)

        def to_external_batch(self, dense):
            return self.external_array()[dense]

    latest = LatestResults(_Vocab())
    latest.set_row(5, [(6, 1.5), (7, 0.5)])
    latest.absorb_batch(TopKBatch(
        np.array([8], np.int32), np.array([[9, 0, 0]], np.int32),
        np.array([[2.5, -np.inf, -np.inf]], np.float32)))
    packed = latest.snapshot().packed()
    assert sorted(packed.rows.tolist()) == [5, 8]
    assert packed.idx.shape[1] == 3  # padded to the widest batch


# ---------------------------------------------------------------------------
# config validation


def test_serve_config_validation():
    with pytest.raises(ValueError, match="serve-port"):
        Config(window_size=10, seed=1, serve_port=70000)
    with pytest.raises(ValueError, match="same port"):
        Config(window_size=10, seed=1, serve_port=9100, metrics_port=9100)
    with pytest.raises(ValueError, match="single-process"):
        Config(window_size=10, seed=1, serve_port=0, coordinator="h:1",
               num_processes=2, process_id=0)
    with pytest.raises(ValueError, match="serve-history"):
        Config(window_size=10, seed=1, serve_history=0)
    with pytest.raises(ValueError, match="serve-stale-after-s"):
        Config(window_size=10, seed=1, serve_stale_after_s=-1.0)
    with pytest.raises(ValueError, match="serve-query-slo-s"):
        Config(window_size=10, seed=1, serve_query_slo_s=-0.1)
    cfg = Config.from_args(["-i", "x", "-ws", "50", "--serve-port", "0",
                            "--serve-history", "16",
                            "--serve-stale-after-s", "30",
                            "--serve-query-slo-s", "0.1"])
    assert cfg.serve_port == 0 and cfg.serve_history == 16
    assert cfg.serve_stale_after_s == 30.0
    assert cfg.serve_query_slo_s == 0.1


def test_recommend_route_errors():
    job = CooccurrenceJob(_cfg(serve_port=0))
    srv = MetricsServer(REGISTRY, port=0, serving=job.serving)
    try:
        code, body = srv.recommend("user=abc")
        assert code == 400
        code, body = srv.recommend("n=0")
        assert code == 400
        code, body = srv.recommend(urllib.parse.urlencode({"n": 3}))
        assert code == 200
        assert json.loads(body.decode())["fallback"] is True
    finally:
        srv.stop()
    srv2 = MetricsServer(REGISTRY, port=0)  # serving not attached
    try:
        code, body = srv2.recommend("n=3")
        assert code == 404 and b"--serve-port" in body
    finally:
        srv2.stop()
