"""Multi-host layer (single-process semantics on the virtual CPU mesh) and
result-pipeline contracts of the pipelined backends."""

import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec as P

from tpu_cooccurrence.parallel.distributed import (
    init_multihost, make_multihost_mesh, put_global)
from tpu_cooccurrence.parallel.mesh import ITEM_AXIS
from tpu_cooccurrence.parallel.sharded import ShardedScorer
from tpu_cooccurrence.ops.device_scorer import DeviceScorer
from tpu_cooccurrence.sampling.reservoir import PairDeltaBatch
from tpu_cooccurrence.state.results import materialize_dense


def _pairs(src, dst, delta):
    return PairDeltaBatch(np.asarray(src, np.int64), np.asarray(dst, np.int64),
                          np.asarray(delta, np.int64))


def test_make_multihost_mesh_covers_all_devices():
    mesh = make_multihost_mesh()
    assert mesh.axis_names == (ITEM_AXIS,)
    assert mesh.devices.size == len(jax.devices())


def test_init_multihost_standalone_noop():
    init_multihost()  # no coordinator: must not raise or hang
    assert jax.process_count() == 1


def test_put_global_sharded_and_replicated():
    mesh = make_multihost_mesh()
    n = mesh.devices.size
    arr = np.arange(n * 4, dtype=np.int32).reshape(n, 4)
    g = put_global(arr, mesh, P(ITEM_AXIS))
    np.testing.assert_array_equal(np.asarray(g), arr)
    assert len(g.addressable_shards) == n
    for shard in g.addressable_shards:
        d = shard.index[0].start or 0
        np.testing.assert_array_equal(np.asarray(shard.data), arr[d:d + 1])
    r = put_global(np.arange(5, dtype=np.int32), mesh, P())
    np.testing.assert_array_equal(np.asarray(r), np.arange(5))


@pytest.mark.parametrize("scorer_cls", ["sharded", "device"])
def test_result_pipeline_lags_one_window_and_flushes(scorer_cls):
    if scorer_cls == "sharded":
        scorer = ShardedScorer(16, 5, num_shards=4)
    else:
        scorer = DeviceScorer(16, 5, use_pallas="off")
    w1 = materialize_dense(scorer.process_window(0, _pairs([1, 2], [2, 1], [1, 1])))
    assert w1 == []  # first window's results are still in flight
    assert scorer.last_dispatched_rows == 2
    w2 = materialize_dense(scorer.process_window(1, _pairs([3], [4], [1])))
    assert sorted(item for item, _ in w1 + w2) == [1, 2]  # window-1 results
    tail = materialize_dense(scorer.flush())
    assert [item for item, _ in tail] == [3]
    assert materialize_dense(scorer.flush()) == []  # idempotent once drained


@pytest.mark.parametrize("scorer_cls", ["sharded", "device"])
def test_restore_clears_pending(scorer_cls):
    if scorer_cls == "sharded":
        scorer = ShardedScorer(16, 5, num_shards=4)
    else:
        scorer = DeviceScorer(16, 5, use_pallas="off")
    snap = scorer.checkpoint_state()
    scorer.process_window(0, _pairs([1, 2], [2, 1], [1, 1]))
    scorer.restore_state(snap)
    # rolled-back results must not surface
    assert materialize_dense(scorer.flush()) == []


# -- init semantics (ISSUE-10 satellite: previously untested) ----------


def test_init_multihost_idempotent_and_probe_does_not_latch(monkeypatch):
    from tpu_cooccurrence.parallel import distributed

    calls = []
    monkeypatch.setattr(distributed, "_initialized", False)
    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda **kw: calls.append(kw))
    # Argument-free probe: a no-op that must NOT latch _initialized —
    # a later real initialize must still go through.
    init_multihost()
    assert calls == [] and not distributed._initialized
    init_multihost("127.0.0.1:1234", 2, 0)
    assert len(calls) == 1 and distributed._initialized
    assert calls[0] == {"coordinator_address": "127.0.0.1:1234",
                        "num_processes": 2, "process_id": 0}
    # Idempotent: a second real call is swallowed (the runtime is up).
    init_multihost("127.0.0.1:1234", 2, 0)
    assert len(calls) == 1


def test_hosts_major_device_ordering():
    """The mesh ordering contract: all of host 0's chips, then host
    1's, ... (ties broken by device id) — contiguous row shards stay
    within a host so the item-axis psum decomposes ICI-first."""
    from tpu_cooccurrence.parallel.distributed import hosts_major

    class Dev:
        def __init__(self, process_index, id):
            self.process_index = process_index
            self.id = id

    devs = [Dev(1, 0), Dev(0, 3), Dev(1, 2), Dev(0, 1)]
    ordered = [(d.process_index, d.id) for d in hosts_major(devs)]
    assert ordered == [(0, 1), (0, 3), (1, 0), (1, 2)]


def test_make_multihost_mesh_single_process_keeps_given_order():
    """Single-process (no multi-controller runtime): the caller's
    device order is preserved verbatim — hosts-major reordering only
    engages when process_count > 1."""
    devs = list(jax.devices())[::-1]
    mesh = make_multihost_mesh(devs)
    assert list(mesh.devices.flat) == devs


# -- collective-entry watchdog -----------------------------------------


def test_collective_watchdog_disarmed_without_env(monkeypatch):
    from tpu_cooccurrence.parallel import distributed

    monkeypatch.delenv(distributed.COLLECTIVE_TIMEOUT_ENV, raising=False)
    exits = []
    monkeypatch.setattr(distributed, "_peer_lost_exit",
                        lambda *a: exits.append(a))
    import threading

    before = threading.active_count()
    with distributed.collective_watchdog("test"):
        assert threading.active_count() == before  # no timer thread
    assert exits == []


def test_collective_watchdog_fires_on_blocked_entry(monkeypatch):
    import time

    from tpu_cooccurrence.parallel import distributed

    monkeypatch.setenv(distributed.COLLECTIVE_TIMEOUT_ENV, "0.05")
    fired = []
    monkeypatch.setattr(distributed, "_peer_lost_exit",
                        lambda label, t: fired.append(label))
    with distributed.collective_watchdog("wedged-collective"):
        deadline = time.time() + 5.0
        while not fired and time.time() < deadline:
            time.sleep(0.01)
    assert fired == ["wedged-collective"]
    # A fast collective cancels its timer: no late fire.
    fired.clear()
    with distributed.collective_watchdog("fast"):
        pass
    time.sleep(0.15)
    assert fired == []


def test_collective_watchdog_fires_barrier_enter_site(monkeypatch):
    from tpu_cooccurrence.parallel import distributed
    from tpu_cooccurrence.robustness import faults

    monkeypatch.delenv(distributed.COLLECTIVE_TIMEOUT_ENV, raising=False)
    # The per-process collective ordinal is process-global state; pin it
    # so the armed seq-2 spec means "the second entry below".
    monkeypatch.setattr(distributed, "_collective_seq", 0)
    faults.arm(["barrier_enter:2:exception"])
    try:
        with distributed.collective_watchdog("one"):
            pass
        with pytest.raises(faults.InjectedFault):
            with distributed.collective_watchdog("two"):
                pass
    finally:
        faults.disarm()
