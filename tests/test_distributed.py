"""Multi-host layer (single-process semantics on the virtual CPU mesh) and
result-pipeline contracts of the pipelined backends."""

import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec as P

from tpu_cooccurrence.parallel.distributed import (
    init_multihost, make_multihost_mesh, put_global)
from tpu_cooccurrence.parallel.mesh import ITEM_AXIS
from tpu_cooccurrence.parallel.sharded import ShardedScorer
from tpu_cooccurrence.ops.device_scorer import DeviceScorer
from tpu_cooccurrence.sampling.reservoir import PairDeltaBatch
from tpu_cooccurrence.state.results import materialize_dense


def _pairs(src, dst, delta):
    return PairDeltaBatch(np.asarray(src, np.int64), np.asarray(dst, np.int64),
                          np.asarray(delta, np.int64))


def test_make_multihost_mesh_covers_all_devices():
    mesh = make_multihost_mesh()
    assert mesh.axis_names == (ITEM_AXIS,)
    assert mesh.devices.size == len(jax.devices())


def test_init_multihost_standalone_noop():
    init_multihost()  # no coordinator: must not raise or hang
    assert jax.process_count() == 1


def test_put_global_sharded_and_replicated():
    mesh = make_multihost_mesh()
    n = mesh.devices.size
    arr = np.arange(n * 4, dtype=np.int32).reshape(n, 4)
    g = put_global(arr, mesh, P(ITEM_AXIS))
    np.testing.assert_array_equal(np.asarray(g), arr)
    assert len(g.addressable_shards) == n
    for shard in g.addressable_shards:
        d = shard.index[0].start or 0
        np.testing.assert_array_equal(np.asarray(shard.data), arr[d:d + 1])
    r = put_global(np.arange(5, dtype=np.int32), mesh, P())
    np.testing.assert_array_equal(np.asarray(r), np.arange(5))


@pytest.mark.parametrize("scorer_cls", ["sharded", "device"])
def test_result_pipeline_lags_one_window_and_flushes(scorer_cls):
    if scorer_cls == "sharded":
        scorer = ShardedScorer(16, 5, num_shards=4)
    else:
        scorer = DeviceScorer(16, 5, use_pallas="off")
    w1 = materialize_dense(scorer.process_window(0, _pairs([1, 2], [2, 1], [1, 1])))
    assert w1 == []  # first window's results are still in flight
    assert scorer.last_dispatched_rows == 2
    w2 = materialize_dense(scorer.process_window(1, _pairs([3], [4], [1])))
    assert sorted(item for item, _ in w1 + w2) == [1, 2]  # window-1 results
    tail = materialize_dense(scorer.flush())
    assert [item for item, _ in tail] == [3]
    assert materialize_dense(scorer.flush()) == []  # idempotent once drained


@pytest.mark.parametrize("scorer_cls", ["sharded", "device"])
def test_restore_clears_pending(scorer_cls):
    if scorer_cls == "sharded":
        scorer = ShardedScorer(16, 5, num_shards=4)
    else:
        scorer = DeviceScorer(16, 5, use_pallas="off")
    snap = scorer.checkpoint_state()
    scorer.process_window(0, _pairs([1, 2], [2, 1], [1, 1]))
    scorer.restore_state(snap)
    # rolled-back results must not surface
    assert materialize_dense(scorer.flush()) == []
