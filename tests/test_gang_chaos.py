"""Whole-gang chaos capstone (ISSUE 10): the real CLI in gang mode.

A 2-process CPU multi-controller gang (local ``jax.distributed``
coordinator, gloo collectives, 1 virtual device per worker) driven by
the real gang supervisor, with process-qualified faults injected:

* ``ckpt_commit@1:<gen>:crash`` kills exactly worker 1 inside the
  epoch-commit window — its generation file is renamed into place but
  no ``EPOCH`` marker exists, and worker 0 is wedged in the commit
  barrier (the collective-entry watchdog or the gang-kill resolves it).
  The gang restarts, the restore vote drags BOTH hosts back to the
  previous epoch (the torn generation quarantined as ``*.partial`` on
  both), and total stdout is bit-identical to an uninterrupted gang
  run — at pipeline depths 0 and 2.

* multi-host ``--degrade``: both workers journal the IDENTICAL
  transition sequence (the per-window worst-signal allgather keeps the
  ladder in lockstep) with sampling parity intact.

The deeper soak (more sites, the journal-staleness wedge detection) is
``slow``-lane; this module's quick variants are tier-1.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ENV = dict(os.environ, JAX_PLATFORMS="cpu",
           XLA_FLAGS="--xla_force_host_platform_device_count=1",
           PALLAS_AXON_POOL_IPS="")


@pytest.fixture(scope="module")
def stream(tmp_path_factory):
    path = tmp_path_factory.mktemp("gang") / "in.csv"
    with open(path, "w") as fh:
        # 350 events = 7 windows at ws 500: enough for the highest
        # chaos ordinal in this module (window/generation 5) with
        # margin, at ~2/3 the wall of the original 500-event stream —
        # the fixture feeds four-plus real gang runs (tier-1 budget).
        for i in range(350):
            fh.write(f"{i % 13},{i % 17},{i * 10}\n")
    return str(path)


def _gang_args(stream, ck_dir, extra):
    return [sys.executable, "-m", "tpu_cooccurrence.cli",
            "-i", stream, "-ws", "500", "-ic", "8", "-uc", "5",
            "-s", "0xC0FFEE", "--backend", "sharded",
            "--num-shards", "2", "--num-items", "32",
            "--checkpoint-dir", ck_dir,
            "--checkpoint-every-windows", "2",
            "--checkpoint-retain", "10",
            "--gang-workers", "2", "--gang-heartbeat-s", "1",
            "--collective-timeout-s", "15",
            "--restart-delay-ms", "0"] + extra


def _run(stream, ck_dir, extra, timeout=420):
    proc = subprocess.run(_gang_args(stream, ck_dir, extra),
                          capture_output=True, text=True, env=ENV,
                          cwd=REPO, timeout=timeout)
    return proc


@pytest.fixture(scope="module")
def clean(stream, tmp_path_factory):
    """One uninterrupted gang run — the parity reference for every
    chaos variant (bit-identical across pipeline depths by the PR-1
    contract, so one reference serves depth 0 and 2)."""
    ck = str(tmp_path_factory.mktemp("gang-clean") / "ck")
    proc = _run(stream, ck, [])
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert proc.stdout, "clean gang run produced no output"
    return proc.stdout


@pytest.mark.parametrize("depth", [0, 2])
def test_gang_ckpt_commit_crash_recovers_bit_identical(
        tmp_path, stream, clean, depth):
    """Kill worker 1 at the generation-2 epoch commit: the gang
    restarts, the restore vote falls back to generation 1 on BOTH
    hosts (torn generation quarantined as *.partial — no torn restore,
    no crash loop), and stdout is bit-identical to the uninterrupted
    run."""
    ck = str(tmp_path / "ck")
    extra = ["--restart-on-failure", "2",
             "--inject-fault", "ckpt_commit@1:2:crash",
             "--fault-state-dir", str(tmp_path / "faults")]
    if depth:
        extra += ["--pipeline-depth", str(depth)]
    proc = _run(stream, ck, extra)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert proc.stdout == clean
    # Exactly worker 1's marker fired (the @proc qualifier held).
    assert sorted(os.listdir(tmp_path / "faults")) == ["fault0.p1.fired"]
    # The torn generation was quarantined on BOTH hosts: worker 1
    # crashed post-rename-pre-marker, worker 0 died wedged in the
    # commit barrier — neither may ever restore generation 2's files.
    partials = sorted(p for p in os.listdir(ck)
                      if p.endswith(".partial"))
    assert partials == ["state.p0.2.npz.partial",
                        "state.p1.2.npz.partial"]
    assert "gang restore vote" in proc.stderr
    assert "gang-restarting" in proc.stderr


def _sparse_gang_args(stream, ck_dir, incremental, extra):
    """Sparse-backend gang (the sharded-sparse mh checkpoint format —
    the topology the incremental delta chain must survive)."""
    return [sys.executable, "-m", "tpu_cooccurrence.cli",
            "-i", stream, "-ws", "500", "-ic", "8", "-uc", "5",
            "-s", "0xC0FFEE", "--backend", "sparse",
            "--num-shards", "2",
            "--checkpoint-dir", ck_dir,
            "--checkpoint-every-windows", "2",
            "--checkpoint-retain", "10",
            "--checkpoint-compact-ratio", "10",
            "--gang-workers", "2", "--gang-heartbeat-s", "1",
            "--collective-timeout-s", "15",
            "--restart-delay-ms", "0"] \
        + (["--checkpoint-incremental"] if incremental else []) + extra


def _run_sparse(stream, ck_dir, incremental, extra, timeout=420):
    return subprocess.run(
        _sparse_gang_args(stream, ck_dir, incremental, extra),
        capture_output=True, text=True, env=ENV, cwd=REPO,
        timeout=timeout)


@pytest.mark.slow
def test_gang_incremental_ckpt_mid_delta_crash_bit_identical(
        tmp_path, stream):
    """ISSUE 12 acceptance: a 2-process sparse gang running INCREMENTAL
    checkpoints, killed inside a DELTA generation's epoch-commit window
    (worker 1 at the generation-2 commit — its npz and delta file are
    renamed into place but no EPOCH marker exists). The restore vote
    counts only fully-committed chains, drags both hosts back to
    generation 1, quarantines the torn generation's npz AND delta as
    *.partial on both, and total stdout is bit-identical to the SAME
    crash recovered from full checkpoints (restore canonicalizes
    within-row slab order, so the full-checkpoint recovery — not an
    uninterrupted run — is the bit-exact comparator, same as every
    sparse resume test): the delta-chain restore is byte-equivalent to
    the full-checkpoint restore in the gang topology."""
    chaos = ["--restart-on-failure", "2",
             "--inject-fault", "ckpt_commit@1:2:crash"]
    ref_ck = str(tmp_path / "ck-full")
    ref = _run_sparse(stream, ref_ck, False,
                      chaos + ["--fault-state-dir",
                               str(tmp_path / "faults-full")])
    assert ref.returncode == 0, ref.stderr[-2000:]
    assert ref.stdout, "full-checkpoint chaos run produced no output"
    assert "gang restore vote" in ref.stderr

    ck = str(tmp_path / "ck")
    proc = _run_sparse(stream, ck, True,
                       chaos + ["--fault-state-dir",
                                str(tmp_path / "faults")])
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert proc.stdout == ref.stdout
    assert "gang restore vote" in proc.stderr
    assert sorted(os.listdir(tmp_path / "faults")) == ["fault0.p1.fired"]
    # The torn DELTA generation was quarantined with its npz on both
    # hosts; the recovered run then rebuilt generation 2 (files exist
    # again) and kept chaining deltas.
    partials = sorted(p for p in os.listdir(ck)
                      if p.endswith(".partial"))
    assert partials == ["delta.p0.2.bin.partial",
                        "delta.p1.2.bin.partial",
                        "state.p0.2.npz.partial",
                        "state.p1.2.npz.partial"]
    for pid in (0, 1):
        assert any(n.startswith(f"delta.p{pid}.")
                   and n.endswith(".bin") for n in os.listdir(ck)), \
            f"no live delta generation for p{pid} after recovery"


def test_gang_degrade_lockstep_journals(tmp_path, stream):
    """--degrade on a multi-host run: the per-window worst-signal
    allgather steps both hosts' ladders identically — the journals
    carry the same (seq, level, events) sequence — and the run
    completes with both partitions emitted (sampling parity)."""
    ck = str(tmp_path / "ck")
    jpath = str(tmp_path / "journal.jsonl")
    proc = _run(stream, ck,
                ["--degrade", "--degrade-window-wall-s", "0.0001",
                 "--degrade-trip-windows", "2", "--journal", jpath])
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert proc.stdout
    seqs = []
    for pid in (0, 1):
        with open(f"{jpath}.p{pid}") as f:
            recs = [json.loads(line) for line in f if line.strip()]
        seqs.append([(r["seq"], r.get("degradation_level"),
                      tuple(r.get("degrade_events", [])))
                     for r in recs if "seq" in r])
    assert seqs[0] == seqs[1], "hosts diverged on the shed ladder"
    levels = {lv for s in seqs for _, lv, _ in s}
    assert max(levels) >= 1, "the tiny wall threshold never tripped"
    # Window records in a multi-host run carry the committed epoch.
    with open(f"{jpath}.p0") as f:
        first = json.loads(next(iter(f)))
    assert "epoch" in first


@pytest.mark.slow
def test_gang_soak_more_sites_and_wedge_detection(tmp_path, stream,
                                                  clean):
    """Slow-lane soak: (a) a worker SIGKILLed mid-window recovers via
    gang restart; (b) a worker wedged alive (600s delay injected in
    the window loop, heartbeats still beating) is detected by the
    JOURNAL-staleness watchdog and the gang restarts — both with
    bit-identical stdout."""
    # (a) plain mid-window crash of worker 0 at window 5.
    ck = str(tmp_path / "ck-a")
    proc = _run(stream, ck,
                ["--restart-on-failure", "2",
                 "--inject-fault", "window_fire@0:5:crash",
                 "--fault-state-dir", str(tmp_path / "faults-a")])
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert proc.stdout == clean
    assert sorted(os.listdir(tmp_path / "faults-a")) == [
        "fault0.p0.fired"]
    # (b) silently wedged peer: worker 1 stalls 600s inside the window
    # loop while its heartbeat thread keeps beating — only the journal
    # watchdog can see it.
    ck = str(tmp_path / "ck-b")
    jpath = str(tmp_path / "journal-b.jsonl")
    proc = _run(stream, ck,
                ["--restart-on-failure", "2",
                 "--journal", jpath,
                 "--watchdog-stale-after-s", "4",
                 "--inject-fault", "window_fire@1:5:delay_ms:600000",
                 "--fault-state-dir", str(tmp_path / "faults-b")])
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert proc.stdout == clean
    assert "journal stale" in proc.stderr
