"""Gang-supervision unit layer: argv derivation, heartbeats, the peer
table, the restore vote, and the GangSupervisor restart loop over fake
workers (the real-CLI chaos capstone lives in ``test_gang_chaos.py``).
"""

import json
import os
import sys
import time

import pytest

from tpu_cooccurrence.observability.http import MetricsServer
from tpu_cooccurrence.observability.registry import MetricsRegistry
from tpu_cooccurrence.robustness import faults
from tpu_cooccurrence.robustness.gang import (
    GANG_SITES,
    GangSupervisor,
    HeartbeatWriter,
    PeerTable,
    agree_restore_generation,
    gang_child_argv,
    heartbeat_path,
)
from tpu_cooccurrence.state import checkpoint as ckpt


# -- argv derivation ----------------------------------------------------


def test_gang_child_argv_strips_supervision_and_appends_identity():
    argv = ["-i", "in.csv", "-ws", "10", "--gang-workers", "2",
            "--restart-on-failure", "3", "--restart-delay-ms", "0",
            "--backend", "sharded"]
    out = gang_child_argv(argv, 1, 2, "127.0.0.1:5000")
    assert "--gang-workers" not in out
    assert "--restart-on-failure" not in out
    assert out[-6:] == ["--coordinator", "127.0.0.1:5000",
                        "--num-processes", "2", "--process-id", "1"]
    assert out[:4] == ["-i", "in.csv", "-ws", "10"]


def test_gang_child_argv_suffixes_per_process_outputs():
    argv = ["--journal", "/tmp/j.jsonl", "--quarantine-file=/tmp/q.jsonl"]
    out0 = gang_child_argv(argv, 0, 2, "c:1")
    out1 = gang_child_argv(argv, 1, 2, "c:1")
    assert "/tmp/j.jsonl.p0" in out0 and "/tmp/j.jsonl.p1" in out1
    assert "--quarantine-file=/tmp/q.jsonl.p0" in out0
    assert "--quarantine-file=/tmp/q.jsonl.p1" in out1


# -- heartbeats ---------------------------------------------------------


def test_heartbeat_writer_touches_file_and_fires_site(tmp_path):
    gang_dir = str(tmp_path / "gang")
    plan = faults.arm(["peer_heartbeat:2:exception"])
    try:
        hb = HeartbeatWriter(gang_dir, 1, interval_s=60.0)
        hb.beat()
        path = heartbeat_path(gang_dir, 1)
        assert os.path.exists(path)
        payload = json.loads(open(path).read())
        assert payload["beat"] == 1
        # Second beat crosses the armed spec's seq and must fire it.
        with pytest.raises(faults.InjectedFault):
            hb.beat()
        assert plan.specs[0].fired
    finally:
        faults.disarm()


def test_heartbeat_thread_beats_periodically(tmp_path):
    gang_dir = str(tmp_path / "gang")
    hb = HeartbeatWriter(gang_dir, 0, interval_s=0.05).start()
    try:
        deadline = time.time() + 5.0
        while hb.beats < 3 and time.time() < deadline:
            time.sleep(0.02)
        assert hb.beats >= 3
        assert os.path.exists(heartbeat_path(gang_dir, 0))
    finally:
        hb.stop()


# -- the peer table + /healthz ------------------------------------------


def _touch_heartbeat(gang_dir, pid, age_s=0.0):
    os.makedirs(gang_dir, exist_ok=True)
    p = heartbeat_path(gang_dir, pid)
    with open(p, "w") as f:
        f.write("{}")
    past = time.time() - age_s
    os.utime(p, (past, past))


def test_peer_table_reports_ages_epochs_and_staleness(tmp_path):
    gang_dir = str(tmp_path / "gang")
    ck_dir = str(tmp_path / "ck")
    os.makedirs(ck_dir)
    _touch_heartbeat(gang_dir, 0, age_s=0.0)
    _touch_heartbeat(gang_dir, 1, age_s=99.0)
    for gen in (1, 2):
        open(os.path.join(ck_dir, f"EPOCH.p0.{gen}"), "w").close()
    open(os.path.join(ck_dir, "EPOCH.p1.1"), "w").close()
    table = PeerTable(gang_dir, 2, stale_after_s=10.0,
                      checkpoint_dir=ck_dir)
    rows, any_stale = table.snapshot()
    assert any_stale
    assert rows[0]["stale"] is False and rows[1]["stale"] is True
    assert rows[0]["committed_epoch"] == 2
    assert rows[1]["committed_epoch"] == 1
    assert rows[1]["heartbeat_age_seconds"] >= 99.0


def test_peer_table_missing_heartbeat_grace_then_stale(tmp_path):
    gang_dir = str(tmp_path / "gang")
    os.makedirs(gang_dir)
    table = PeerTable(gang_dir, 1, stale_after_s=10.0)
    rows, any_stale = table.snapshot()
    # No beat yet, but inside the startup grace: not stale.
    assert not any_stale
    assert rows[0]["heartbeat_age_seconds"] is None
    table._started_unix -= 120.0  # age the table past the grace
    rows, any_stale = table.snapshot()
    assert any_stale and rows[0]["stale"]


def test_peer_table_stale_after_zero_disables_staleness(tmp_path):
    """--gang-stale-after-s 0 means staleness handling OFF (matching
    the supervisor's _stale_worker): /healthz must not drain a healthy
    gang on heartbeat age."""
    gang_dir = str(tmp_path / "gang")
    _touch_heartbeat(gang_dir, 0, age_s=9999.0)
    table = PeerTable(gang_dir, 1, stale_after_s=0.0)
    rows, any_stale = table.snapshot()
    assert not any_stale
    assert rows[0]["stale"] is False


def test_healthz_carries_peers_and_503s_on_stale(tmp_path):
    import urllib.request

    gang_dir = str(tmp_path / "gang")
    _touch_heartbeat(gang_dir, 0, age_s=0.0)
    _touch_heartbeat(gang_dir, 1, age_s=500.0)
    reg = MetricsRegistry()
    server = MetricsServer(
        reg, port=0,
        peers=PeerTable(gang_dir, 2, stale_after_s=60.0)).start()
    try:
        url = f"http://127.0.0.1:{server.port}/healthz"
        try:
            urllib.request.urlopen(url)
            raise AssertionError("expected 503")
        except urllib.error.HTTPError as exc:
            assert exc.code == 503
            payload = json.loads(exc.read().decode())
        assert payload["status"] == "peer_stale"
        peers = payload["peers"]
        assert [p["process"] for p in peers] == [0, 1]
        assert peers[1]["stale"] is True
    finally:
        server.stop()


def test_healthz_peers_all_fresh_is_healthy(tmp_path):
    import urllib.request

    gang_dir = str(tmp_path / "gang")
    _touch_heartbeat(gang_dir, 0)
    _touch_heartbeat(gang_dir, 1)
    reg = MetricsRegistry()
    server = MetricsServer(
        reg, port=0,
        peers=PeerTable(gang_dir, 2, stale_after_s=60.0)).start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/healthz") as resp:
            payload = json.loads(resp.read().decode())
        assert len(payload["peers"]) == 2
        assert not any(p["stale"] for p in payload["peers"])
    finally:
        server.stop()


# -- the restore vote ---------------------------------------------------


def _write_gen(directory, suffix, gen, marker=True):
    os.makedirs(directory, exist_ok=True)
    with open(os.path.join(directory,
                           f"state{suffix}.{gen}.npz"), "wb") as f:
        f.write(b"x")
    if marker:
        open(os.path.join(directory, f"EPOCH{suffix}.{gen}"), "w").close()


def test_vote_quarantines_uncommitted_above_agreed(tmp_path):
    d = str(tmp_path / "ck")
    _write_gen(d, ".p0", 1)
    _write_gen(d, ".p0", 2, marker=False)  # crashed pre-commit
    # This host committed 1; the (fake) gang agreed on 1 too.
    agreed = agree_restore_generation(d, ".p0", exchange=lambda v: v)
    assert agreed == 1
    assert os.path.exists(os.path.join(d, "state.p0.2.npz.partial"))
    assert not os.path.exists(os.path.join(d, "state.p0.2.npz"))
    assert ckpt.generations(d, ".p0")[0][0] == 1


def test_vote_peer_missing_commit_drags_this_host_back(tmp_path):
    d = str(tmp_path / "ck")
    _write_gen(d, ".p0", 1)
    _write_gen(d, ".p0", 2, marker=True)  # committed HERE...
    # ...but the peer's vote says its newest committed is 1.
    agreed = agree_restore_generation(d, ".p0",
                                      exchange=lambda v: min(v, 1))
    assert agreed == 1
    assert os.path.exists(os.path.join(d, "state.p0.2.npz.partial"))
    # The stale marker is dropped with the quarantined generation.
    assert not os.path.exists(os.path.join(d, "EPOCH.p0.2"))


def test_vote_fresh_directory_is_noop(tmp_path):
    d = str(tmp_path / "ck")
    seen = []

    def exch(v):
        seen.append(v)
        return v

    assert agree_restore_generation(d, ".p0", exchange=exch) == -1
    assert seen == [-1]


def test_vote_legacy_directory_without_markers_uses_newest_gen(tmp_path):
    # Pre-epoch checkpoints (no markers at all) keep restoring: the
    # per-host vote falls back to the newest generation file.
    d = str(tmp_path / "ck")
    _write_gen(d, ".p0", 3, marker=False)
    _write_gen(d, ".p0", 2, marker=False)
    assert agree_restore_generation(d, ".p0", exchange=lambda v: v) == 3
    assert os.path.exists(os.path.join(d, "state.p0.3.npz"))


# -- the gang supervisor over fake workers ------------------------------


FAKE_WORKER = r"""
import json, os, sys, time
args = sys.argv[1:]
def val(flag):
    return args[args.index(flag) + 1]
pid = int(val("--process-id"))
state_dir = val("-i")  # the test smuggles its scratch dir as the input
mode = val("-ws")      # and the scenario name as the window size slot
gang_dir = os.environ["TPU_COOC_GANG_DIR"]
hb = os.path.join(gang_dir, f"heartbeat.p{pid}")
open(hb, "w").write("{}")
if mode == "clean":
    print(f"row-from-p{pid}")
    sys.exit(0)
if mode == "fail-once":
    marker = os.path.join(state_dir, f"failed.p{pid}")
    if pid == 1 and not os.path.exists(marker):
        open(marker, "w").close()
        sys.exit(9)
    print(f"row-from-p{pid}")
    sys.exit(0)
if mode == "permanent":
    sys.exit(78 if pid == 0 else 0)
if mode == "wedge":
    # One beat, then silence: the stale-heartbeat monitor must kill us.
    time.sleep(600)
if mode == "skew":
    # Worker 0 finishes immediately (its heartbeat legitimately
    # freezes); worker 1 keeps working well past stale_after_s.
    if pid == 0:
        print(f"row-from-p{pid}")
        sys.exit(0)
    t0 = time.time()
    while time.time() - t0 < 3.0:
        open(hb, "w").write("{}")
        time.sleep(0.2)
    print(f"row-from-p{pid}")
    sys.exit(0)
sys.exit(3)
"""


def _fake_gang(tmp_path, mode, attempts=1, stale_after_s=0.0,
               timeout_s=60.0):
    script = tmp_path / "fake_worker.py"
    script.write_text(FAKE_WORKER)

    class Sink:
        def __init__(self):
            self.text = ""

        def write(self, s):
            self.text += s

    sink = Sink()
    sup = GangSupervisor(
        ["-i", str(tmp_path), "-ws", mode], num_workers=2,
        attempts=attempts, gang_dir=str(tmp_path / "gang"),
        stale_after_s=stale_after_s, delay_s=0.0, timeout_s=timeout_s,
        stdout=sink, python=[sys.executable, str(script)])
    return sup, sink


def test_gang_supervisor_forwards_clean_output_in_process_order(tmp_path):
    sup, sink = _fake_gang(tmp_path, "clean")
    assert sup.run() == 0
    assert sink.text == "row-from-p0\nrow-from-p1\n"


def test_gang_supervisor_restarts_whole_gang_on_one_death(tmp_path):
    sup, sink = _fake_gang(tmp_path, "fail-once", attempts=2)
    assert sup.run() == 0
    # Attempt 1's partial output (worker 0 printed before the gang was
    # killed) is discarded; only the clean attempt's spools forward.
    assert sink.text == "row-from-p0\nrow-from-p1\n"


def test_gang_supervisor_exhausts_attempts(tmp_path):
    script = tmp_path / "fake_worker.py"
    script.write_text(FAKE_WORKER)
    sup, _ = _fake_gang(tmp_path, "fail-once", attempts=0)
    assert sup.run() == 9


def test_gang_supervisor_permanent_code_never_retries(tmp_path):
    sup, _ = _fake_gang(tmp_path, "permanent", attempts=5)
    t0 = time.monotonic()
    assert sup.run() == 78
    assert time.monotonic() - t0 < 30  # no backoff-retry loop


def test_gang_supervisor_ignores_exited_workers_staleness(tmp_path):
    """A worker that exited cleanly freezes its heartbeat by design;
    while peers finish a skewed tail past stale_after_s the monitor
    must not read that as peer death and kill a completing gang."""
    sup, sink = _fake_gang(tmp_path, "skew", stale_after_s=1.0)
    assert sup.run() == 0
    assert sink.text == "row-from-p0\nrow-from-p1\n"


def test_gang_supervisor_kills_gang_on_stale_heartbeat(tmp_path):
    sup, _ = _fake_gang(tmp_path, "wedge", attempts=0,
                        stale_after_s=1.0)
    t0 = time.monotonic()
    assert sup.run() == 124
    # Killed by staleness (~1s + poll), nowhere near the 600s sleep.
    assert time.monotonic() - t0 < 30


def test_gang_supervisor_rejects_gang_of_one(tmp_path):
    with pytest.raises(ValueError):
        GangSupervisor([], num_workers=1, attempts=0,
                       gang_dir=str(tmp_path / "g"))


def test_gang_sites_are_registered():
    for site in GANG_SITES:
        assert site in faults.SITES
