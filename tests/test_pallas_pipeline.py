"""End-to-end pipeline with the Pallas scorer forced on (interpret mode)."""

import numpy as np

from tpu_cooccurrence.config import Backend, Config

from test_pipeline import random_stream, run_production


def test_pipeline_pallas_on_matches_xla():
    kw = dict(window_size=10, seed=0xBEEF, item_cut=5, user_cut=4,
              num_items=30)
    users, items, ts = random_stream(17, n=250)
    xla = run_production(
        Config(**kw, backend=Backend.DEVICE, pallas="off"), users, items, ts)
    pls = run_production(
        Config(**kw, backend=Backend.DEVICE, pallas="on"), users, items, ts)
    assert set(xla.latest) == set(pls.latest)
    for item in xla.latest:
        a = xla.latest[item]
        b = pls.latest[item]
        assert len(a) == len(b)
        np.testing.assert_allclose(
            np.array([s for _, s in b]), np.array([s for _, s in a]),
            rtol=1e-5, atol=1e-5)
