"""Calibrated dataset stand-ins (VERDICT r4, Next #5).

No real MovieLens/Instacart files can exist in this environment (zero
egress), so the benchmark stand-ins are generators whose MARGINALS are
fitted to the datasets' published spectra. These tests pin the
calibration: the analytic laws hit the published anchors, the generated
streams carry them, and the bench configs record the model label.
Residual deltas vs the real data: docs/calibrated_standins.md.
"""

import numpy as np

from tpu_cooccurrence.io import synthetic as syn

ML25M_EVENTS = syn.ML25M_EVENTS  # 25,000,095 (dataset README)


def _law(cal, n_key="n_items"):
    return syn.zipf_mandelbrot_weights(cal[n_key], cal["item_s"],
                                       cal["item_q"])


def test_ml25m_item_law_hits_published_head():
    w = _law(syn.ML25M_CALIBRATION)
    counts = ML25M_EVENTS * w
    # Top-1 = Forrest Gump's 81,491 ratings; the near-tied head
    # (top3/top1 = 0.978) is the shape a pure Zipf cannot produce.
    assert abs(counts[0] - 81_491) < 5
    assert abs(counts[2] - 79_672) < 5
    assert abs(w[2] / w[0] - 79_672 / 81_491) < 1e-4
    # Mean ratings/movie is automatic: total / items.
    assert abs(counts.mean() - ML25M_EVENTS / 59_047) < 0.1
    np.testing.assert_allclose(w.sum(), 1.0, rtol=1e-12)


def test_ml100k_item_law_hits_published_head():
    w = _law(syn.ML100K_CALIBRATION)
    counts = 100_000 * w
    assert abs(counts[0] - 583) < 2   # Star Wars (1977)
    assert abs(counts[2] - 508) < 2   # Fargo


def test_instacart_item_law_hits_published_head():
    c = syn.INSTACART_CALIBRATION
    w = syn.zipf_mandelbrot_weights(c["n_products"], c["item_s"],
                                    c["item_q"])
    counts = 33_819_106 * w
    assert abs(counts[0] - 491_291) < 500   # Banana
    assert abs(counts[2] - 275_577) < 500   # Organic Strawberries


def test_ml25m_stream_marginals():
    n = 500_000
    users, items, ts = syn.ml25m_calibrated(n)
    assert len(users) == len(items) == len(ts) == n
    # Exact user multiplicities: every one of the 162,541 users appears
    # (largest-remainder assignment of a min-20-anchored activity law
    # scaled to n), and the mean matches the thinned target exactly.
    cnt = np.bincount(users, minlength=162_541)
    assert cnt.sum() == n
    assert (users >= 0).all() and users.max() < 162_541
    assert abs(cnt.mean() - n / 162_541) < 1e-9
    # Item head: expected top-1 = 81,491 * (n / 25M) ~ 1,630; iid draw
    # relative sd ~2.5%, so +-6 sigma stays well inside 20%.
    top = np.sort(np.bincount(items))[::-1]
    expect = 81_491 * n / ML25M_EVENTS
    assert abs(top[0] - expect) < 0.2 * expect
    # Near-tied head survives sampling: top-3 within 15% of top-1.
    assert top[2] > 0.85 * top[0]
    assert (np.diff(ts) >= 0).all()
    # Deterministic per seed.
    u2, i2, t2 = syn.ml25m_calibrated(n)
    assert (u2 == users).all() and (i2 == items).all()


def test_ml100k_stream_respects_user_floor():
    users, items, ts = syn.ml100k_calibrated()
    cnt = np.bincount(users, minlength=943)
    # Published floor: every user rated >= 20 movies. Largest-remainder
    # assignment keeps the clipped law's floor within rounding (+-1).
    assert cnt.min() >= 19
    assert abs(cnt.mean() - 100_000 / 943) < 1e-9
    assert 55 <= np.median(cnt) <= 80   # published median ~65
    assert items.max() < 1_682


def test_instacart_stream_basket_shape():
    users, items, ts = syn.instacart_calibrated(20_000)
    # Basket structure: one (user, ts) group per order, ~10.1 items
    # mean, sizes in [1, 145].
    n_baskets = len(np.unique(ts))
    assert n_baskets == 20_000
    sizes = np.bincount((ts // 10).astype(np.int64))
    assert 1 <= sizes.min() and sizes.max() <= 145
    assert abs(sizes.mean() - 10.1) < 0.5
    assert 6 <= np.median(sizes) <= 10   # published median ~8
    # Users scale with the basket budget at the real 16.6 orders/user.
    n_users = len(np.unique(users))
    assert abs(n_users - 20_000 / 16.6) < 0.1 * (20_000 / 16.6)


def test_bench_configs_record_standin_model(monkeypatch):
    """Stand-in rows carry standin_model=calibrated-v1; real-file rows
    must not (the field is provenance for the synthetic path only)."""
    from tpu_cooccurrence.bench import configs
    from tpu_cooccurrence.config import Backend

    monkeypatch.delenv("MOVIELENS_100K", raising=False)
    # Provenance is decided by which stream path ran, not by its length
    # — truncate the calibrated stand-in so the label check doesn't pay
    # for a full 100k-event oracle measurement (tier-1 budget).
    real_100k = configs._movielens_100k
    def _small_100k():
        u, i, t, model = real_100k()
        return u[:12_000], i[:12_000], t[:12_000], model
    monkeypatch.setattr(configs, "_movielens_100k", _small_100k)
    r = configs.config2_ml100k(backend=Backend.ORACLE)
    d = r.as_dict()
    assert d["synthetic_standin"] is True
    assert d["standin_model"] == "calibrated-v1"
    # The tiny-text config is not a stand-in for anything: no label.
    r1 = configs.config1_tiny_text(backend=Backend.ORACLE)
    assert "standin_model" not in r1.as_dict()
