"""Graceful-degradation plane (ISSUE 5): controller, shedding, breaker.

Contracts under test:

* **Hysteresis** — escalation only after `trip_windows` consecutive
  overloaded windows, de-escalation only after `clear_windows` healthy
  ones, exactly one level per decision (no flapping, no jumps).
* **NORMAL parity** — with the controller installed but never leaving
  NORMAL (and quarantine off), per-window outputs are bit-identical to
  the seed path at pipeline depths 0 and 2.
* **Shedding monotonicity** — tighter cuts never *add* pairs: the
  tighter mask/pair set is a subset of the looser one.
* **Overload soak** — a stream forced into sustained overload completes
  (no deadlock, no watchdog needed), and the journal shows monotone
  one-step level transitions.
* **Quarantine / provenance / breaker / healthz** — the satellite
  fixes, end-to-end through the CLI where the wiring lives.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from tpu_cooccurrence.config import Backend, Config
from tpu_cooccurrence.io.synthetic import zipfian_interactions
from tpu_cooccurrence.job import CooccurrenceJob
from tpu_cooccurrence.observability.registry import REGISTRY
from tpu_cooccurrence.robustness import degrade
from tpu_cooccurrence.robustness.degrade import (
    DegradationController,
    DegradationLevel,
    LEVEL_EVENTS,
    TRANSITION_RULES,
    ScorerCircuitBreaker,
)

from test_cli import write_stream

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ENV = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")


@pytest.fixture(autouse=True)
def _clean_plane():
    """No controller or metrics may leak between tests."""
    REGISTRY.reset()
    degrade.uninstall()
    yield
    degrade.uninstall()
    REGISTRY.reset()


# ---------------------------------------------------------------------------
# controller state machine


def _controller(**kw):
    kw.setdefault("window_wall_s", 1.0)
    kw.setdefault("trip_windows", 3)
    kw.setdefault("clear_windows", 4)
    kw.setdefault("pause_ms", 0)
    return DegradationController(**kw)


def test_escalation_needs_consecutive_overload():
    c = _controller(trip_windows=3)
    # Two bad, one good, two bad, ... never three in a row -> NORMAL.
    for _ in range(5):
        c.observe_window(2.0)
        c.observe_window(2.0)
        c.observe_window(0.01)
    assert c.level == DegradationLevel.NORMAL


def test_escalates_one_level_per_trip_and_caps_at_pause():
    c = _controller(trip_windows=2)
    seen = []
    for _ in range(20):
        level, events = c.observe_window(2.0)
        seen.append(level)
    assert c.level == DegradationLevel.PAUSE_INGEST
    # Monotone, one step at a time.
    for a, b in zip(seen, seen[1:]):
        assert b - a in (0, 1)


def test_deescalation_needs_clear_windows_and_steps_down():
    c = _controller(trip_windows=1, clear_windows=3)
    c.observe_window(2.0)
    assert c.level == DegradationLevel.SHED_SAMPLING
    c.observe_window(0.01)
    c.observe_window(0.01)
    assert c.level == DegradationLevel.SHED_SAMPLING  # not yet
    _, events = c.observe_window(0.01)
    assert c.level == DegradationLevel.NORMAL
    assert events == [LEVEL_EVENTS["NORMAL"]]


def test_ring_saturation_and_stall_count_as_overload():
    c = _controller(trip_windows=1)
    c.observe_window(0.01, ring_depth=2, ring_capacity=2)
    assert c.level == DegradationLevel.SHED_SAMPLING
    c2 = _controller(trip_windows=1)
    c2.observe_window(0.01, stall_seconds=0.9)
    assert c2.level == DegradationLevel.SHED_SAMPLING


def test_queue_wait_marks_next_window_overloaded():
    c = _controller(trip_windows=1)
    c.note_queue_wait(0.9)
    c.observe_window(0.01)
    assert c.level == DegradationLevel.SHED_SAMPLING


def test_effective_knobs_identity_at_normal_and_monotone_by_level():
    c = _controller()
    assert c.effective_item_cut(500) == 500
    assert c.effective_user_cut(500) == 500
    assert c.effective_top_k(10) == 10
    prev_cut, prev_k = 500, 10
    for _ in range(3):  # walk up the ladder
        for _ in range(c.trip_windows):
            c.observe_window(2.0)
        assert c.effective_item_cut(500) <= prev_cut
        assert c.effective_top_k(10) <= prev_k
        prev_cut, prev_k = c.effective_item_cut(500), c.effective_top_k(10)
    assert c.level == DegradationLevel.PAUSE_INGEST
    assert c.effective_item_cut(500) == 500 // 4
    assert c.effective_top_k(10) == 5
    assert c.effective_item_cut(1) == 1  # never below 1


def test_pause_ingest_admission_is_bounded_not_a_stall():
    c = _controller(trip_windows=1, pause_ms=10)
    c.observe_window(2.0)
    c.observe_window(2.0)
    c.observe_window(2.0)
    assert c.level == DegradationLevel.PAUSE_INGEST
    # admit() returns (bounded delay), it does not block until recovery.
    assert c.admit() == pytest.approx(0.01)
    assert c.admit() == pytest.approx(0.01)


def test_stale_ingest_escalates_once_per_period(monkeypatch):
    c = _controller(stale_after_s=10.0)
    t = [1000.0]
    monkeypatch.setattr(degrade.time, "monotonic", lambda: t[0])
    c.observe_window(0.01)  # a window completed at t=1000
    t[0] += 11.0
    c.admit()
    assert c.level == DegradationLevel.SHED_SAMPLING
    c.admit()  # same stale period: no second step
    assert c.level == DegradationLevel.SHED_SAMPLING
    t[0] += 11.0
    c.admit()
    assert c.level == DegradationLevel.SHED_K


def test_stale_escalation_event_journaled_on_next_window(monkeypatch):
    """An admission-side (stale-ingest) transition must not vanish from
    the journal: its event token is drained into the NEXT observed
    window's record."""
    c = _controller(stale_after_s=10.0)
    t = [1000.0]
    monkeypatch.setattr(degrade.time, "monotonic", lambda: t[0])
    c.observe_window(0.01)
    t[0] += 11.0
    c.admit()  # escalates on the ingest thread, no window record yet
    assert c.level == DegradationLevel.SHED_SAMPLING
    level, events = c.observe_window(0.01)
    assert level == int(DegradationLevel.SHED_SAMPLING)
    assert events == [LEVEL_EVENTS["SHED_SAMPLING"]]
    _, events = c.observe_window(0.01)
    assert events == []  # drained exactly once


def test_stale_gate_covers_first_dispatch_wedge(monkeypatch):
    """A scorer that wedges before the FIRST window completes must
    still trip the stale gate — staleness is measured from controller
    construction until a window lands."""
    t = [1000.0]
    monkeypatch.setattr(degrade.time, "monotonic", lambda: t[0])
    c = _controller(stale_after_s=10.0)
    c.admit()
    assert c.level == DegradationLevel.NORMAL  # within warm-up
    t[0] += 11.0
    c.admit()  # no window EVER completed; ingest still arriving
    assert c.level == DegradationLevel.SHED_SAMPLING


def test_every_level_has_rule_and_event():
    for member in DegradationLevel:
        assert member.name in TRANSITION_RULES
        assert member.name in LEVEL_EVENTS
    assert len(set(LEVEL_EVENTS.values())) == len(LEVEL_EVENTS)


# ---------------------------------------------------------------------------
# shedding monotonicity: tighter cuts never ADD pairs


def test_item_cut_mask_monotone_under_tighter_cut():
    from tpu_cooccurrence.sampling.item_cut import ItemInteractionCut

    rng = np.random.default_rng(0)
    items = rng.integers(0, 30, 500)
    loose = ItemInteractionCut(8, capacity=64)
    tight = ItemInteractionCut(8, capacity=64)
    tight.set_effective_cut(3)
    m_loose = loose.fire(items)
    m_tight = tight.fire(items)
    # Pointwise: sampled under the tighter cut => sampled under the looser.
    assert not np.any(m_tight & ~m_loose)
    assert m_tight.sum() < m_loose.sum()


def test_sliding_sampler_pairs_subset_under_tighter_cuts():
    from tpu_cooccurrence.sampling.sliding import SlidingBasketSampler

    rng = np.random.default_rng(1)
    users = rng.integers(0, 12, 400).astype(np.int64)
    items = rng.integers(0, 40, 400).astype(np.int64)

    def pair_multiset(item_cut, user_cut):
        s = SlidingBasketSampler(8, 6, skip_cuts=False)
        s.set_effective_cuts(item_cut, user_cut)
        out = s.fire(users, items)
        from collections import Counter

        return Counter(zip(out.src.tolist(), out.dst.tolist()))

    loose = pair_multiset(8, 6)
    for cuts in [(4, 6), (8, 3), (4, 3), (2, 2)]:
        tight = pair_multiset(*cuts)
        assert all(tight[p] <= loose[p] for p in tight), cuts


def test_effective_cut_clamps_to_configured_and_floor():
    from tpu_cooccurrence.sampling.item_cut import ItemInteractionCut

    cut = ItemInteractionCut(10, capacity=16)
    cut.set_effective_cut(999)
    assert cut.effective_cut == 10  # tighten-only
    cut.set_effective_cut(0)
    assert cut.effective_cut == 1  # never zero


def test_topk_batch_truncated_and_rescorer_knob():
    from tpu_cooccurrence.state.rescorer import HostRescorer
    from tpu_cooccurrence.state.results import TopKBatch

    b = TopKBatch(np.arange(3, dtype=np.int32),
                  np.arange(12, dtype=np.int32).reshape(3, 4),
                  np.linspace(4, 1, 12, dtype=np.float32).reshape(3, 4))
    t = b.truncated(2)
    assert t.idx.shape == (3, 2) and t.vals.shape == (3, 2)
    assert b.truncated(4) is b  # identity when wide enough
    r = HostRescorer(10)
    r.set_effective_top_k(3)
    assert r.effective_top_k == 3
    r.set_effective_top_k(99)
    assert r.effective_top_k == 10  # tighten-only


# ---------------------------------------------------------------------------
# NORMAL parity: controller installed, never leaves NORMAL -> bit-identical


def _run_job(users, items, ts, depth, backend="oracle", **cfg_kw):
    REGISTRY.reset()
    degrade.uninstall()
    cfg = Config(window_size=100, seed=7, item_cut=50, user_cut=50,
                 backend=Backend(backend), pipeline_depth=depth, **cfg_kw)
    job = CooccurrenceJob(cfg)
    emitted = []
    job.on_update = lambda out: emitted.append(
        [(int(r), None) for r in out.rows] if hasattr(out, "rows")
        else [(i, tuple(top)) for i, top in out])
    for lo in range(0, len(users), 997):
        job.add_batch(users[lo:lo + 997], items[lo:lo + 997],
                      ts[lo:lo + 997])
    job.finish()
    return job, emitted


@pytest.mark.parametrize("depth", [0, 2])
@pytest.mark.parametrize("backend", ["oracle", "sparse"])
def test_normal_parity_bit_identical(depth, backend):
    users, items, ts = zipfian_interactions(
        8000, n_items=300, n_users=120, alpha=1.1, seed=3, events_per_ms=40)
    seed_job, seed_em = _run_job(users, items, ts, depth, backend)
    norm_job, norm_em = _run_job(users, items, ts, depth, backend,
                                 degrade=True,
                                 degrade_window_wall_s=1e9,
                                 degrade_stale_after_s=1e9)
    assert seed_job.counters.as_dict() == norm_job.counters.as_dict()
    assert seed_job.windows_fired == norm_job.windows_fired
    assert set(seed_job.latest) == set(norm_job.latest)
    for item in seed_job.latest:
        assert seed_job.latest[item] == norm_job.latest[item], item
    assert seed_em == norm_em


# ---------------------------------------------------------------------------
# overload soak: sheds, survives, journals monotone transitions


def test_overload_soak_completes_and_journal_levels_monotone(tmp_path):
    """A stream forced into sustained overload (wall threshold below any
    real window) must escalate with hysteresis, keep completing windows
    (bounded admission — no deadlock), and journal every level step."""
    users, items, ts = zipfian_interactions(
        12000, n_items=300, n_users=120, alpha=1.1, seed=5,
        events_per_ms=5)
    jpath = tmp_path / "journal.jsonl"
    job, _ = _run_job(users, items, ts, 2, "oracle",
                      degrade=True,
                      degrade_window_wall_s=1e-9,  # every window overloaded
                      degrade_trip_windows=2,
                      degrade_pause_ms=1,
                      journal=str(jpath))
    assert job.windows_fired > 10
    from tpu_cooccurrence.observability.journal import read_records

    recs = list(read_records(str(jpath)))
    levels = [r["degradation_level"] for r in recs]
    assert levels[-1] == int(DegradationLevel.PAUSE_INGEST)
    # Monotone one-step escalation, never a jump, never a dip (the
    # overload is sustained, so nothing should de-escalate).
    for a, b in zip(levels, levels[1:]):
        assert b - a in (0, 1), levels
    # Hysteresis: at least trip_windows records between distinct levels.
    changes = [i for i, (a, b) in enumerate(zip(levels, levels[1:]))
               if b != a]
    for c1, c2 in zip(changes, changes[1:]):
        assert c2 - c1 >= 2
    # Transition events journaled exactly where the level steps.
    for i in changes:
        assert recs[i + 1].get("degrade_events"), recs[i + 1]
    assert int(REGISTRY.gauge("cooc_shed_events_total").get()) > 0
    # Shedding really tightened the applied cut.
    assert job.item_cut.effective_cut < job.config.item_cut


# ---------------------------------------------------------------------------
# scorer circuit breaker (unit; the CLI chaos case lives in test_chaos.py)


class _FlakyScorer:
    accepts_aggregated = True

    def __init__(self, fail_windows):
        self.fail_windows = set(fail_windows)
        self.calls = 0
        self.last_dispatched_rows = 0

    def process_window(self, ts, pairs):
        self.calls += 1
        if self.calls in self.fail_windows:
            raise RuntimeError(f"injected dispatch failure {self.calls}")
        return [(1, [(2, 1.0)])]

    def flush(self):
        return []


def _pairs():
    from tpu_cooccurrence.sampling.reservoir import PairDeltaBatch

    return PairDeltaBatch(np.array([1]), np.array([2]),
                          np.array([1], dtype=np.int32))


def test_breaker_trips_after_threshold_and_probes_back():
    b = ScorerCircuitBreaker(_FlakyScorer({2, 3}), top_k=5,
                             threshold=2, probe_after_windows=2)
    assert b.process_window(0, _pairs()) and b.breaker_state == "closed"
    b.process_window(1, _pairs())          # failure 1: still closed
    assert b.breaker_state == "closed"
    b.process_window(2, _pairs())          # failure 2: trip
    assert b.breaker_state == "open" and b.trips == 1
    b.process_window(3, _pairs())          # open: fallback, primary idle
    assert b.primary.calls == 3
    b.process_window(4, _pairs())          # half-open probe succeeds
    assert b.breaker_state == "closed"
    assert int(REGISTRY.gauge("cooc_scorer_breaker_trips_total").get()) == 1


def test_breaker_failed_probe_reopens():
    b = ScorerCircuitBreaker(_FlakyScorer({1, 2}), top_k=5,
                             threshold=1, probe_after_windows=2)
    b.process_window(0, _pairs())   # primary call 1 fails -> trip
    assert b.breaker_state == "open"
    b.process_window(1, _pairs())   # open: fallback (primary idle)
    b.process_window(2, _pairs())   # half-open probe (primary call 2)
    assert b.breaker_state == "open" and b.trips == 2


def test_breaker_every_window_scored_on_fallback():
    """No window's pairs are dropped: failures route to the fallback,
    which accumulates its own exact state."""
    b = ScorerCircuitBreaker(_FlakyScorer(range(1, 100)), top_k=5,
                             threshold=1, probe_after_windows=1000)
    outs = [b.process_window(i, _pairs()) for i in range(6)]
    assert all(len(o) == 1 for o in outs)
    # Fallback is the exact host rescorer and saw every delta.
    assert b._fallback.observed == 6


def test_breaker_flush_keeps_fallback_rows_authoritative():
    """Once tripped, the primary's (stale) flush must not overwrite
    items the fallback has since scored — its rows are filtered out of
    the final absorption; items only the primary saw still flow."""
    from tpu_cooccurrence.state.results import TopKBatch

    class DeferredPrimary(_FlakyScorer):
        def flush(self):
            # Stale device table covering items 1 and 9.
            return TopKBatch(np.array([1, 9], np.int32),
                             np.zeros((2, 3), np.int32),
                             np.ones((2, 3), np.float32))

    b = ScorerCircuitBreaker(DeferredPrimary(range(1, 100)), top_k=3,
                             threshold=1, probe_after_windows=1000)
    b.process_window(0, _pairs())  # trip; fallback scores item 1
    assert b.breaker_state == "open" and 1 in b._fallback_owned
    flushed = b.flush()
    assert flushed.rows.tolist() == [9]  # item 1 belongs to the fallback

    # Recovery reclaims ownership: the primary re-scoring item 1 makes
    # its table authoritative again, so the flush emits both rows.
    b3 = ScorerCircuitBreaker(DeferredPrimary({1}), top_k=3,
                              threshold=1, probe_after_windows=1)
    b3.process_window(0, _pairs())  # call 1 fails -> trip, fallback owns 1
    b3.process_window(1, _pairs())  # half-open probe: call 2 re-scores 1
    assert b3.breaker_state == "closed" and not b3._fallback_owned
    assert b3.flush().rows.tolist() == [1, 9]

    class FailingFlushPrimary(DeferredPrimary):
        def flush(self):
            raise RuntimeError("device gone")

    b2 = ScorerCircuitBreaker(FailingFlushPrimary(range(1, 100)), top_k=3,
                              threshold=1, probe_after_windows=1000)
    b2.process_window(0, _pairs())
    assert b2.flush() == []  # dropped, not raised — run completes


def test_admission_side_transition_written_as_journal_event(
        tmp_path, monkeypatch):
    """With a journal attached, a stale-ingest escalation reaches disk
    immediately as an out-of-band event record — even though no window
    ever completes again (the exact scenario the path exists for)."""
    from tpu_cooccurrence.observability.journal import (
        RunJournal, read_records, validate_record)

    jpath = tmp_path / "j.jsonl"
    journal = RunJournal(str(jpath))
    c = _controller(stale_after_s=10.0)
    import time as _time

    c.journal_event = lambda event: journal.record(
        {"v": 1, "event": event, "wall_unix": round(_time.time(), 3)})
    t = [1000.0]
    monkeypatch.setattr(degrade.time, "monotonic", lambda: t[0])
    c.observe_window(0.01)
    t[0] += 11.0
    c.admit()  # escalates; no further window will ever be observed
    journal.close()
    recs = list(read_records(str(jpath)))
    assert len(recs) == 1
    validate_record(recs[0])
    assert recs[0]["event"] == LEVEL_EVENTS["SHED_SAMPLING"]
    # And it is NOT double-journaled by a later window drain.
    _, events = c.observe_window(0.01)
    assert events == []


def test_breaker_delegates_to_primary_attributes():
    class P(_FlakyScorer):
        defer_results = True
        custom_knob = 42

    b = ScorerCircuitBreaker(P(()), top_k=5)
    assert b.defer_results is True and b.custom_knob == 42
    assert b.accepts_aggregated is True


# (test_degrade_rejected_on_multihost was retired by ISSUE 10: the
# blanket multi-host rejection became the per-window worst-signal
# allgather — see test_multihost_degrade_config_now_accepted below and
# the gang chaos lockstep test in test_gang_chaos.py.)


def test_breaker_config_validation():
    with pytest.raises(ValueError, match="oracle backend IS"):
        Config(window_size=10, backend=Backend.ORACLE,
               scorer_breaker_threshold=1)
    with pytest.raises(ValueError, match="single-process"):
        Config(window_size=10, backend=Backend.SPARSE, num_shards=2,
               scorer_breaker_threshold=1)
    job = CooccurrenceJob(Config(window_size=10, backend=Backend.SPARSE,
                                 scorer_breaker_threshold=2, seed=1))
    assert isinstance(job.scorer, ScorerCircuitBreaker)


# ---------------------------------------------------------------------------
# parse provenance + quarantine through the CLI (the wiring under test)


def test_cli_parse_error_names_path_and_line(tmp_path):
    f = tmp_path / "in.csv"
    f.write_text("1,100,5\n2,101,6\nPOISONED-LINE\n3,102,7\n")
    proc = subprocess.run(
        [sys.executable, "-m", "tpu_cooccurrence.cli", "-i", str(f),
         "-ws", "10", "--backend", "oracle"],
        capture_output=True, text=True, env=ENV, cwd=REPO, timeout=300)
    assert proc.returncode != 0
    assert f"{f}:3" in proc.stderr
    assert "POISONED-LINE" in proc.stderr


def test_cli_quarantine_diverts_and_run_completes(tmp_path):
    f = tmp_path / "in.csv"
    write_stream(f, n=400)
    lines = f.read_text().splitlines()
    lines.insert(100, "garbage,line")
    lines.insert(200, "1,2,3,4,5")
    f.write_text("\n".join(lines) + "\n")
    dead = tmp_path / "dead.jsonl"
    proc = subprocess.run(
        [sys.executable, "-m", "tpu_cooccurrence.cli", "-i", str(f),
         "-ws", "40", "-ic", "8", "-uc", "5", "-s", "0xC0FFEE",
         "--backend", "oracle", "--quarantine-file", str(dead),
         "--max-quarantine-rate", "0.5"],
        capture_output=True, text=True, env=ENV, cwd=REPO, timeout=300)
    assert proc.returncode == 0, proc.stderr[-800:]
    assert proc.stdout  # results still emitted
    recs = [json.loads(l) for l in dead.read_text().splitlines()]
    assert len(recs) == 2
    assert recs[0]["path"] == str(f) and recs[0]["lineno"] == 101
    assert recs[0]["raw"] == "garbage,line"
    assert recs[1]["lineno"] == 201


def test_cli_quarantine_rate_breaker_exits_2_even_for_short_input(tmp_path):
    """The min_lines warm-up only defers the MID-stream trip; the
    end-of-stream check applies the pure rate, so a short fully-garbage
    input exits 2 instead of 'succeeding' with zero output."""
    f = tmp_path / "in.csv"
    f.write_text("\n".join("junk-%d" % i for i in range(300)) + "\n")
    proc = subprocess.run(
        [sys.executable, "-m", "tpu_cooccurrence.cli", "-i", str(f),
         "-ws", "10", "--backend", "oracle",
         "--quarantine-file", str(tmp_path / "dead.jsonl"),
         "--max-quarantine-rate", "0.01"],
        capture_output=True, text=True, env=ENV, cwd=REPO, timeout=300)
    assert proc.returncode == 2
    assert "quarantine rate breaker tripped" in proc.stderr


def test_quarantine_check_final_waives_warmup_but_respects_rate():
    import tempfile

    from tpu_cooccurrence.robustness.quarantine import (
        Quarantine, QuarantineRateExceeded)

    d = tempfile.mkdtemp()
    q = Quarantine(os.path.join(d, "dead.jsonl"), max_rate=0.5)
    q.note_lines(10)
    for i in range(3):  # 30% < 50%: under the rate, final check passes
        q.quarantine("f", i, "junk", "bad")
    q.check_final()
    q2 = Quarantine(os.path.join(d, "dead2.jsonl"), max_rate=0.1)
    q2.note_lines(10)
    for i in range(3):  # 30% > 10%, but seen < min_lines: no mid-trip
        q2.quarantine("f", i, "junk", "bad")
    with pytest.raises(QuarantineRateExceeded):
        q2.check_final()


def test_cli_quarantine_rate_breaker_exits_2(tmp_path):
    f = tmp_path / "in.csv"
    f.write_text("\n".join("junk-%d" % i for i in range(2000)) + "\n")
    proc = subprocess.run(
        [sys.executable, "-m", "tpu_cooccurrence.cli", "-i", str(f),
         "-ws", "10", "--backend", "oracle",
         "--quarantine-file", str(tmp_path / "dead.jsonl"),
         "--max-quarantine-rate", "0.01"],
        capture_output=True, text=True, env=ENV, cwd=REPO, timeout=300)
    assert proc.returncode == 2
    assert "quarantine rate breaker tripped" in proc.stderr


# ---------------------------------------------------------------------------
# /healthz degradation fields (satellite: paused must not read healthy)


def test_healthz_reports_level_and_refuses_healthy_while_paused():
    from tpu_cooccurrence.observability.http import MetricsServer
    from tpu_cooccurrence.observability.registry import MetricsRegistry

    reg = MetricsRegistry()
    srv = MetricsServer(reg, stale_after_s=300.0)
    payload, healthy = srv.health()
    assert healthy and payload["degradation_level"] == 0
    assert payload["quarantined_total"] == 0
    reg.gauge("cooc_last_window_unix_seconds").set(__import__("time").time())
    reg.gauge("cooc_quarantined_lines_total").set(7)
    reg.gauge("cooc_degradation_level").set(
        int(DegradationLevel.PAUSE_INGEST))
    payload, healthy = srv.health()
    assert not healthy and payload["status"] == "paused"
    assert payload["degradation_level"] == 3
    assert payload["quarantined_total"] == 7
    # De-escalated: healthy again (window is recent).
    reg.gauge("cooc_degradation_level").set(int(DegradationLevel.SHED_K))
    payload, healthy = srv.health()
    assert healthy and payload["status"] == "ok"
    srv.stop()


def test_config_degrade_validation():
    with pytest.raises(ValueError, match="shed-factor"):
        Config(window_size=10, degrade_shed_factor=1)
    with pytest.raises(ValueError, match="quarantine-rate"):
        Config(window_size=10, max_quarantine_rate=0.0)
    with pytest.raises(ValueError, match="trip-windows"):
        Config(window_size=10, degrade_trip_windows=0)


# -- dead-letter rotation (--max-quarantine-bytes, ISSUE-10 satellite) --


def test_quarantine_rotation_caps_active_file(tmp_path):
    import json as _json

    from tpu_cooccurrence.robustness.quarantine import (
        QUARANTINE_BACKUPS, Quarantine)

    path = str(tmp_path / "dead.jsonl")
    q = Quarantine(path, max_rate=1.0, max_bytes=400)
    q.note_lines(10_000)
    for i in range(40):
        q.quarantine("in.csv", i + 1, "x" * 40, "bad line")
    q.close()
    assert q.rotations > 0
    # Active file stays under the cap; numbered backups exist and are
    # bounded by the keep window.
    assert os.path.getsize(path) <= 400
    backups = sorted(p.name for p in tmp_path.iterdir()
                     if p.name.startswith("dead.jsonl."))
    assert backups and len(backups) <= QUARANTINE_BACKUPS
    # Every surviving line is still intact JSONL (rotation never tears
    # a record), and the run-total counter survived the rotations.
    kept = 0
    for p in [path] + [str(tmp_path / b) for b in backups]:
        with open(p) as f:
            for line in f:
                _json.loads(line)
                kept += 1
    assert q.quarantined == 40 and kept <= 40


def test_quarantine_rotation_shifts_backups_and_drops_oldest(tmp_path):
    from tpu_cooccurrence.robustness.quarantine import (
        QUARANTINE_BACKUPS, Quarantine)

    path = str(tmp_path / "dead.jsonl")
    q = Quarantine(path, max_rate=1.0, max_bytes=150)
    q.note_lines(100_000)
    for i in range(60):
        q.quarantine("in.csv", i + 1, "y" * 30, "bad")
    q.close()
    assert q.rotations > QUARANTINE_BACKUPS  # oldest really dropped
    assert not os.path.exists(f"{path}.{QUARANTINE_BACKUPS + 1}")


def test_quarantine_unbounded_without_cap(tmp_path):
    from tpu_cooccurrence.robustness.quarantine import Quarantine

    path = str(tmp_path / "dead.jsonl")
    q = Quarantine(path, max_rate=1.0)
    q.note_lines(10_000)
    for i in range(50):
        q.quarantine("in.csv", i + 1, "z" * 40, "bad")
    q.close()
    assert q.rotations == 0
    assert not os.path.exists(path + ".1")


def test_max_quarantine_bytes_validation():
    from tpu_cooccurrence.config import Config
    from tpu_cooccurrence.robustness.quarantine import Quarantine

    with pytest.raises(ValueError, match="max-quarantine-bytes"):
        Config(window_size=10, max_quarantine_bytes=-1)
    with pytest.raises(ValueError, match="max_bytes"):
        Quarantine("/tmp/x.jsonl", max_bytes=-5)


# -- multi-host worst-signal exchange (ISSUE-10 degrade plane) ---------


def test_exchange_vote_drives_ladder_from_peer_signal():
    """A host whose OWN windows are healthy must still escalate when a
    peer votes overloaded — the exchange returns the gang max."""
    c = DegradationController(window_wall_s=1.0, trip_windows=2,
                              clear_windows=2)
    votes = []

    def exchange(local):
        votes.append(local)
        return 1  # a peer is drowning

    c.exchange = exchange
    for _ in range(2):
        level, _ = c.observe_window(wall_seconds=0.001)
    assert level == int(DegradationLevel.SHED_SAMPLING)
    assert votes == [0, 0]  # this host's local signal stayed healthy


def test_exchange_vote_clears_when_gang_healthy():
    c = DegradationController(window_wall_s=1.0, trip_windows=1,
                              clear_windows=2)
    c.exchange = lambda local: local  # single-host gang: identity
    c.observe_window(wall_seconds=9.0)  # trip
    assert c.level == DegradationLevel.SHED_SAMPLING
    c.observe_window(wall_seconds=0.001)
    level, _ = c.observe_window(wall_seconds=0.001)
    assert level == int(DegradationLevel.NORMAL)


def test_exchange_disables_admission_side_stale_escalation():
    """Wall-clock staleness is per-host-nondeterministic: with an
    exchange attached the admit() gate must never move the ladder."""
    c = DegradationController(window_wall_s=1.0, trip_windows=3,
                              stale_after_s=0.001)
    c.exchange = lambda local: local
    c._started_monotonic -= 100.0  # way past stale
    c.admit()
    assert c.level == DegradationLevel.NORMAL
    # Control: without the exchange the same state escalates.
    c2 = DegradationController(window_wall_s=1.0, trip_windows=3,
                               stale_after_s=0.001)
    c2._started_monotonic -= 100.0
    c2.admit()
    assert c2.level == DegradationLevel.SHED_SAMPLING


def test_multihost_degrade_config_now_accepted():
    """The PR-5 blanket rejection is gone: --degrade rides multi-host
    at depth 0; pipelined multi-host degrade is still rejected (the
    vote would race the sampling thread)."""
    from tpu_cooccurrence.config import Config

    Config(window_size=10, degrade=True, coordinator="h:1",
           num_processes=2, process_id=0)
    with pytest.raises(ValueError, match="pipeline-depth 0"):
        Config(window_size=10, degrade=True, coordinator="h:1",
               num_processes=2, process_id=0, pipeline_depth=1)
