"""Fault-injection worker for the supervisor test.

Runs the real CLI, but on the FIRST attempt (marker file absent) arms a
watcher thread that SIGKILLs the process the moment the first periodic
checkpoint lands — a hard crash the in-process code cannot intercept.
Subsequent attempts run clean. Usage:

    python supervised_crash_worker.py <ckpt_dir> <marker> <cli args...>
"""

import os
import signal
import sys
import threading
import time


def main() -> int:
    ck, marker = sys.argv[1], sys.argv[2]
    if not os.path.exists(marker):
        with open(marker, "w"):
            pass

        def watch() -> None:
            # Any committed snapshot (generation-numbered state.<g>.npz,
            # or the legacy un-numbered state.npz).
            import glob

            pat = os.path.join(ck, "state*.npz")
            while not glob.glob(pat):
                time.sleep(0.05)
            os.kill(os.getpid(), signal.SIGKILL)

        threading.Thread(target=watch, daemon=True).start()
    # Run as a plain script: the package lives in the repo root, one
    # level above this file's directory.
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from tpu_cooccurrence.cli import main as cli_main

    return cli_main(sys.argv[3:])


if __name__ == "__main__":
    sys.exit(main())
