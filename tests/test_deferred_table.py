"""DeferredResultsTable unit tests (shared by dense + sparse scorers)."""

import numpy as np
import jax.numpy as jnp

from tpu_cooccurrence.ops.device_scorer import DeferredResultsTable


def _packed(rows_vals, k):
    """Build a [2, S, K] packed block: vals descending, ids bitcast."""
    s = len(rows_vals)
    vals = np.full((s, k), -np.inf, np.float32)
    ids = np.zeros((s, k), np.int32)
    for i, (val, idx) in enumerate(rows_vals):
        vals[i, : len(val)] = val
        ids[i, : len(idx)] = idx
    return jnp.stack([jnp.asarray(vals),
                      jnp.asarray(ids).view(jnp.float32)])


def test_drain_empty_and_incremental():
    t = DeferredResultsTable(top_k=3, items_cap=8)
    assert len(t.drain()) == 0          # nothing scattered yet
    t.ensure()
    t.scatter(_packed([([5.0, 2.0], [7, 1])], 3),
              np.asarray([4], np.int32))
    t.mark(np.asarray([4]))
    b = t.drain()
    assert list(b.rows) == [4]
    np.testing.assert_allclose(b.vals[0, :2], [5.0, 2.0])
    assert list(b.idx[0, :2]) == [7, 1]
    assert len(t.drain()) == 0          # drained rows are clean

    # A re-scatter of the same row after drain is dirty again.
    t.scatter(_packed([([9.0], [2])], 3), np.asarray([4], np.int32))
    t.mark(np.asarray([4]))
    b2 = t.drain()
    assert list(b2.rows) == [4]
    np.testing.assert_allclose(b2.vals[0, 0], 9.0)


def test_sentinel_rows_do_not_scatter():
    t = DeferredResultsTable(top_k=2, items_cap=4)
    t.ensure()
    sent = np.asarray([0, np.iinfo(np.int32).max], np.int32)
    t.scatter(_packed([([1.0], [3]), ([8.0], [2])], 2), sent)
    t.mark(np.asarray([0]))
    b = t.drain()
    assert list(b.rows) == [0]
    np.testing.assert_allclose(b.vals[0, 0], 1.0)  # row 0 kept its block;
    # the padded entry (sentinel) was dropped, not written anywhere


def test_resize_preserves_entries_and_marks():
    t = DeferredResultsTable(top_k=2, items_cap=4)
    t.ensure()
    t.scatter(_packed([([3.0, 1.0], [1, 2])], 2), np.asarray([2], np.int32))
    t.mark(np.asarray([2]))
    t.resize(16)
    assert t.tbl.shape == (2, 16, 2)
    t.scatter(_packed([([4.0], [9])], 2), np.asarray([11], np.int32))
    t.mark(np.asarray([11]))
    b = t.drain()
    assert list(b.rows) == [2, 11]
    np.testing.assert_allclose(b.vals[0, :2], [3.0, 1.0])
    np.testing.assert_allclose(b.vals[1, 0], 4.0)


def test_float_ids_decode():
    t = DeferredResultsTable(top_k=2, items_cap=4)
    t.ensure()
    vals = jnp.asarray(np.array([[7.0, 6.0]], np.float32))
    ids_as_floats = jnp.asarray(np.array([[3.0, 1.0]], np.float32))
    t.scatter(jnp.stack([vals, ids_as_floats]), np.asarray([1], np.int32))
    t.mark(np.asarray([1]))
    b = t.drain(float_ids=True)
    assert list(b.idx[0]) == [3, 1]


def test_reset_clears_everything():
    t = DeferredResultsTable(top_k=2, items_cap=4)
    t.ensure()
    t.scatter(_packed([([1.0], [0])], 2), np.asarray([3], np.int32))
    t.mark(np.asarray([3]))
    t.reset(8)
    assert t.tbl is None
    assert len(t.drain()) == 0


def test_drain_survives_transient_fetch_failure(monkeypatch):
    """A fetch failure must leave the dirty marks set so a retrying
    caller still drains the rows (failure-atomic drain)."""
    import tpu_cooccurrence.ops.device_scorer as ds

    t = DeferredResultsTable(top_k=2, items_cap=8)
    t.ensure()
    t.scatter(_packed([([4.0, 1.0], [2, 5])], 2), np.asarray([3], np.int32))
    t.mark(np.asarray([3]))

    real = ds._gather_packed
    calls = {"n": 0}

    def flaky(tbl, rows):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("transient link failure")
        return real(tbl, rows)

    monkeypatch.setattr(ds, "_gather_packed", flaky)
    try:
        t.drain()
    except RuntimeError:
        pass
    else:
        raise AssertionError("expected the injected failure to propagate")
    b = t.drain()  # retry: rows are still dirty
    assert list(b.rows) == [3]
    np.testing.assert_allclose(b.vals[0, :2], [4.0, 1.0])
    assert len(t.drain()) == 0
