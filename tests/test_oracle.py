"""Oracle pipeline tests: hand-computed tiny cases plus invariants.

Running with ``development_mode=True`` activates the reference's invariant
checks inside the oracle itself (row-sum-vs-materialized-row consistency,
NaN detection, feedback sanity — SURVEY.md §4)."""

import numpy as np
import pytest

from tpu_cooccurrence.config import Backend, Config
from tpu_cooccurrence.metrics import (
    ITEM_LATE_ELEMENTS,
    OBSERVED_COOCCURRENCES,
    RESCORED_ITEMS,
    ROW_SUM_PROCESS_WINDOW,
    USER_LATE_ELEMENTS,
)
from tpu_cooccurrence.oracle import OracleJob
from tpu_cooccurrence.oracle.reference import _llr_scalar


def make_config(**kw):
    kw.setdefault("window_size", 10)
    kw.setdefault("seed", 42)
    kw.setdefault("development_mode", True)
    kw.setdefault("backend", Backend.ORACLE)
    return Config(**kw)


def test_tiny_skip_cuts_hand_checked():
    cfg = make_config(skip_cuts=True, top_k=10)
    job = OracleJob(cfg)
    # Window [0, 10): user 1 interacts with items 10 then 20.
    job.process(1, 10, 1)
    job.process(1, 20, 2)
    # Window [10, 20): user 2 with item 10; user 1 with item 10 again.
    job.process(2, 10, 12)
    job.process(1, 10, 15)
    job.finish()

    # After window 1: C[10][20] = C[20][10] = 1; row sums 10:1, 20:1; obs 2.
    # After window 2: user 1 history [10, 20] gains another 10 ->
    #   pairs (10,10)x2, (10,20), (20,10); C[10][10]=2, C[10][20]=2,
    #   C[20][10]=2; row sums 10:4, 20:2; observed 6.
    assert job.item_rows[10] == {20: 2, 10: 2}
    assert job.item_rows[20] == {10: 2}
    assert job.global_row_sums[10] == 4
    assert job.global_row_sums[20] == 2
    assert job.observed_cooccurrences == 6
    assert job.counters.get(OBSERVED_COOCCURRENCES) == 6
    assert job.counters.get(ROW_SUM_PROCESS_WINDOW) == 6
    # Window1 scores rows 10 and 20; window2 scores rows 10 and 20 again.
    assert job.counters.get(RESCORED_ITEMS) == 4

    # Check an actual LLR value end-to-end for row 10 -> other 20 at the end:
    # k11=2, rowSum(10)=4 -> k12=2, rowSum(20)=2 -> k21=0, k22=6+2-2-0=6.
    expect = _llr_scalar(2, 2, 0, 6)
    final_row10 = dict(job.latest[10])
    assert final_row10[20] == pytest.approx(expect, rel=1e-12)
    # Diagonal entry (10,10) is a legitimate candidate (duplicate history).
    assert 10 in final_row10


def test_late_elements_dropped_and_counted():
    cfg = make_config(skip_cuts=True)
    job = OracleJob(cfg)
    job.process(1, 10, 100)
    job.process(1, 20, 50)  # ts < max_seen -> late (wm = 99)
    job.finish()
    assert job.counters.get(ITEM_LATE_ELEMENTS) == 1
    assert job.counters.get(USER_LATE_ELEMENTS) == 1
    # The late interaction must not appear anywhere.
    assert 20 not in job.item_rows
    assert job.user_history[1] == [10]


def test_equal_timestamps_not_late():
    cfg = make_config(skip_cuts=True)
    job = OracleJob(cfg)
    job.process(1, 10, 100)
    job.process(1, 20, 100)  # equal ts: wm = 99 < 100 -> on time
    job.finish()
    assert job.counters.get(USER_LATE_ELEMENTS) == 0
    assert job.item_rows[10] == {20: 1}


def test_item_cut_tags_first_fmax():
    cfg = make_config(item_cut=2, user_cut=500)
    job = OracleJob(cfg)
    # Three users hit item 99 in the same window; only first two sampled.
    job.process(1, 99, 1)
    job.process(2, 99, 2)
    job.process(3, 99, 3)
    job.finish()
    assert job.item_interactions[99] == 2
    # Unsampled interaction still counts toward user 3's reservoir denominator.
    assert job.user_total[3] == 1
    assert job.user_history[3] == []


def test_item_cut_is_cumulative_across_windows():
    cfg = make_config(item_cut=2)
    job = OracleJob(cfg)
    job.process(1, 99, 1)
    job.process(2, 99, 12)
    job.process(3, 99, 23)  # third acceptance attempt, over the cut
    job.finish()
    assert job.item_interactions[99] == 2
    assert job.user_history[3] == []


def test_reservoir_replace_and_reject_semantics():
    """With user_cut=2, the third+ sampled interactions either replace a slot
    (emitting balanced +/- deltas) or reject (feedback decrement). The
    dev-mode row-sum invariant validates the delta bookkeeping on every
    window."""
    cfg = make_config(user_cut=2, item_cut=500, seed=7)
    job = OracleJob(cfg)
    ts = 1
    for item in range(100, 140):
        job.process(1, item, ts)
        ts += 10  # one window each -> every interaction processed separately
    job.finish()
    assert len(job.user_history[1]) == 2
    assert job.user_total[1] == 40
    # Row sums must globally balance: observed == sum of all row sums and
    # equals the sum over materialized rows.
    total = sum(sum(r.values()) for r in job.item_rows.values())
    assert total == job.observed_cooccurrences
    assert sum(job.global_row_sums.values()) == job.observed_cooccurrences
    # Feedback decrements: item counter never negative, and for 40 singleton
    # items each was accepted at most once.
    assert all(0 <= c <= 1 for c in job.item_interactions.values())


def test_reservoir_matches_full_recount():
    """Property test (SURVEY §4): incrementally maintained C equals a full
    recount from the final user histories... only when no evictions occur.
    With evictions, C reflects the historical pairing sequence; here we
    choose user_cut large enough that the reservoir only appends, so the
    delta-sum must equal the direct recount of sum_u outer(h_u) off-diag
    (with multiplicity)."""
    rng = np.random.default_rng(3)
    cfg = make_config(user_cut=500, item_cut=500, window_size=5)
    job = OracleJob(cfg)
    events = []
    ts = 0
    for _ in range(300):
        ts += int(rng.integers(0, 3))
        events.append((int(rng.integers(0, 10)), int(rng.integers(0, 30)), ts))
    for u, i, t in events:
        job.process(u, i, t)
    job.finish()

    expect = {}
    for _u, hist in job.user_history.items():
        m = {}
        for x in hist:
            m[x] = m.get(x, 0) + 1
        for x, cx in m.items():
            for y, cy in m.items():
                if x == y:
                    if cx > 1:
                        expect[(x, x)] = expect.get((x, x), 0) + cx * (cx - 1)
                else:
                    expect[(x, y)] = expect.get((x, y), 0) + cx * cy

    got = {}
    for i, row in job.item_rows.items():
        for j, c in row.items():
            if c != 0:
                got[(i, j)] = c
    assert got == expect


def test_sampled_mode_respects_cuts_invariants():
    rng = np.random.default_rng(11)
    cfg = make_config(user_cut=3, item_cut=4, window_size=7, seed=123)
    job = OracleJob(cfg)
    ts = 0
    for _ in range(500):
        ts += int(rng.integers(0, 2))
        job.process(int(rng.integers(0, 20)), int(rng.integers(0, 15)), ts)
    job.finish()
    for u, h in job.user_history.items():
        assert len(h) <= 3
    for i, c in job.item_interactions.items():
        assert 0 <= c <= 4
    assert sum(job.global_row_sums.values()) == job.observed_cooccurrences


def test_results_stream_shape():
    cfg = make_config(skip_cuts=True, top_k=2)
    job = OracleJob(cfg)
    job.process(1, 1, 1)
    job.process(1, 2, 2)
    job.process(1, 3, 3)
    job.finish()
    assert job.results, "expected emissions"
    r = job.results[0]
    assert r.timestamp == 9  # window [0,10) maxTimestamp
    assert len(r.top_k) <= 2
    scores = [s for _, s in r.top_k]
    assert scores == sorted(scores, reverse=True)
