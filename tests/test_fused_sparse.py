"""Fused one-dispatch SPARSE window path (--fused-window): parity + routing.

The contract under test (ISSUE 11): with the fused path forced on, every
steady-state sparse window runs packed-wire decode + slab update scatter
+ device registry sync + LLR rescore + results-table scatter as ONE
device program, BIT-identical to the chained sparse path (and matching
the host oracle to tolerance) at pipeline depths 0 and 2 — across the
edges: empty windows, single-pair windows, score-bucket boundaries,
narrow cell dtypes, packed and raw wire. Non-routable windows — slab
relocation, narrow→wide promotion, spill re-promotion — must fall back
to the chained path per window with identical results, and the journal
/ metrics split must record which path each window took.
"""

import json

import numpy as np
import pytest

from tpu_cooccurrence.config import Backend, Config
from tpu_cooccurrence.observability.registry import REGISTRY

from test_fused_window import _ladder_edge_stream, _run_job, _table
from test_pipeline import assert_latest_close


def _run_sparse(users, items, ts, **overrides):
    kw = dict(backend=Backend.SPARSE)
    kw.update(overrides)
    return _run_job(users, items, ts, **kw)


def _wide_row_stream():
    """One hub item co-occurring with ~300 partners (row len crosses the
    16 → 64 → 1024 score-bucket ladder), then repeat touches of the SAME
    cells (the zero-relocation steady state), then a fresh growth spurt.
    """
    users, items, ts = [], [], []

    def ev(u, i, t):
        users.append(u)
        items.append(i)
        ts.append(t)

    for j in range(120):                     # window 1: hub grows wide
        ev(j % 6, 0, 5)
        ev(j % 6, 1 + j, 5)
    for w in range(2, 6):                    # windows 2-5: same cells
        for j in range(40):
            ev(j % 6, 1 + j, w * 10 + 5)
    for j in range(150):                     # window 6: growth again
        ev(j % 6, 200 + j, 65)
    ev(0, 999, 85)                           # flush
    return (np.asarray(users), np.asarray(items),
            np.asarray(ts, dtype=np.int64))


# -- end-to-end parity: edges, depths 0 + 2, oracle ---------------------


@pytest.mark.parametrize("depth", [0, 2])
def test_fused_sparse_bit_identical_to_chained_at_edges(depth):
    users, items, ts = _ladder_edge_stream()
    kw = dict(user_cut=4, item_cut=500, pipeline_depth=depth)
    chained = _run_sparse(users, items, ts, fused_window="off", **kw)
    fused = _run_sparse(users, items, ts, fused_window="on", **kw)
    # Bit-identical: same rows, same ids, same float32 scores — the
    # fused program shares _update_body and _score_rect with chained.
    assert _table(chained) == _table(fused)
    assert chained.counters.as_dict() == fused.counters.as_dict()
    assert chained.windows_fired == fused.windows_fired


def test_fused_sparse_matches_host_oracle():
    # Depth 2 is covered transitively: fused == chained bit-for-bit at
    # both depths above, and chained-vs-oracle is pinned by the
    # existing sparse parity suite.
    users, items, ts = _ladder_edge_stream()
    kw = dict(user_cut=4, item_cut=500)
    oracle = _run_job(users, items, ts, backend=Backend.ORACLE, **kw)
    fused = _run_sparse(users, items, ts, fused_window="on", **kw)
    assert_latest_close(_table(oracle), _table(fused))


@pytest.mark.parametrize("wire", ["raw", "packed"])
def test_fused_sparse_wire_formats_bit_identical(wire):
    """Wire compression and fusion compose: the packed form decodes in
    the fused program's prologue, the raw form ships the buffer — both
    bit-identical to the chained path under the same wire setting. The
    wide-row stream also drives rows across score-bucket widths (16 →
    64 → 1024) with steady repeat windows in between, so plan growth
    and all-padding top-up rectangles are exercised too."""
    users, items, ts = _wide_row_stream()
    kw = dict(user_cut=6, item_cut=500, wire_format=wire)
    chained = _run_sparse(users, items, ts, fused_window="off", **kw)
    fused = _run_sparse(users, items, ts, fused_window="on", **kw)
    assert _table(chained) == _table(fused)
    assert chained.counters.as_dict() == fused.counters.as_dict()


def test_fused_sparse_pallas_rectangles_bit_identical():
    """--pallas on routes kernel-carriable buckets (R >= 256) through
    pallas_score_rect INSIDE the fused program; results stay
    bit-identical to the chained path with the same kernel routing."""
    users, items, ts = _wide_row_stream()
    kw = dict(user_cut=6, item_cut=500, pallas="on")
    chained = _run_sparse(users, items, ts, fused_window="off", **kw)
    fused = _run_sparse(users, items, ts, fused_window="on", **kw)
    assert _table(chained) == _table(fused)


@pytest.mark.parametrize("cell_dtype", ["int16", "int8"])
def test_fused_sparse_narrow_cells_promotion_forces_chained(cell_dtype):
    """Narrow cell dtypes: a hot row crossing the promote threshold
    moves to the wide side-table — that window (and every later window
    touching the wide row) routes chained; output stays bit-identical
    and the promotion really happened."""
    rng = np.random.default_rng(13)
    n = 2200
    # Reservoir replacement bounds a row's sum by ~2 * users * user_cut,
    # so the user count (not the event count) is what pushes the hub row
    # past int8's 128 promote threshold.
    users = rng.integers(0, 40, n)
    # Zipf-ish: item 0 dominates so it sits in most users' reservoirs.
    items = np.where(rng.random(n) < 0.4, 0,
                     rng.integers(1, 60, n)).astype(np.int64)
    ts = np.sort(rng.integers(0, 300, n)).astype(np.int64)
    kw = dict(user_cut=6, item_cut=500, cell_dtype=cell_dtype)
    chained = _run_sparse(users, items, ts, fused_window="off", **kw)
    fused = _run_sparse(users, items, ts, fused_window="on", **kw)
    assert _table(chained) == _table(fused)
    scorer = fused.scorer
    if cell_dtype == "int8":
        assert scorer.wide_rows.any(), "stream never promoted a row"


def test_fused_sparse_spill_repromotion_bit_identical():
    """Tiered store on: windows that re-promote spilled rows carry promo
    sections and route chained; spill-on fused output equals spill-on
    chained output bit-for-bit, and rows really spilled."""
    users, items, ts = [], [], []
    rng = np.random.default_rng(3)
    # Cohort churn: each window its own users/items, so earlier rows go
    # cold; late windows re-touch window-0 items (re-promotion).
    for w in range(8):
        base = 0 if w >= 6 else w * 40
        for _ in range(160):
            users.append(int(rng.integers(0, 5)) + w * 10)
            items.append(base + int(rng.integers(0, 30)))
            ts.append(w * 10 + 5)
    users, items, ts = (np.asarray(users), np.asarray(items),
                        np.asarray(ts, dtype=np.int64))
    kw = dict(user_cut=6, item_cut=500, spill_threshold_windows=2,
              spill_target_hbm_frac=0.0)
    REGISTRY.reset()
    chained = _run_sparse(users, items, ts, fused_window="off", **kw)
    fused = _run_sparse(users, items, ts, fused_window="on", **kw)
    assert _table(chained) == _table(fused)
    assert REGISTRY.gauge("cooc_spill_evictions_total").get() > 0
    assert REGISTRY.gauge("cooc_spill_promotions_total").get() > 0


def test_fused_sparse_checkpoint_restore_resumes_identically():
    """Kill-and-resume across the fused path: the device registry
    mirror is rebuilt from the restored index (all-dirty resync), and
    the resumed run's output is bit-identical to the uninterrupted one.
    """
    import tpu_cooccurrence.state.sparse_scorer as ss
    from tpu_cooccurrence.sampling.reservoir import PairDeltaBatch

    def window(seed):
        r = np.random.default_rng(seed)
        src = r.integers(0, 120, 500)
        dst = r.integers(0, 120, 500)
        m = dst == src
        dst[m] = (dst[m] + 1) % 120
        return PairDeltaBatch(src.astype(np.int64), dst.astype(np.int64),
                              np.ones(500, dtype=np.int32))

    def resume_run(fused):
        first = ss.SparseDeviceScorer(
            top_k=5, defer_results=True, fused_window=fused,
            wire_format="packed", cell_dtype="int16",
            capacity=1 << 16, items_capacity=1 << 10)
        for w in range(5):
            first.process_window(w * 10, window(w))
        first.flush()  # results before the snapshot are drained
        blob = first.checkpoint_state()
        resumed = ss.SparseDeviceScorer(
            top_k=5, defer_results=True, fused_window=fused,
            wire_format="packed", cell_dtype="int16",
            capacity=1 << 16, items_capacity=1 << 10)
        resumed.restore_state(blob)
        for w in range(5, 10):
            resumed.process_window(w * 10, window(w))
        return resumed.flush()

    def rows_of(b):
        return {int(r): (list(map(int, i)), list(map(float, v)))
                for r, i, v in zip(b.rows, b.idx, b.vals)}

    # Restore re-lays each row's cells in key order (canonical blob), so
    # equal-score ties may sit differently than in an uninterrupted run
    # — checkpoint semantics that predate this path. The fused resume
    # must be bit-identical to the CHAINED resume over the identical
    # restore schedule: the rebuilt device registry mirror (all-dirty
    # resync) reproduces the chained path's layout exactly.
    assert rows_of(resume_run("on")) == rows_of(resume_run("off"))


# -- journal + metrics --------------------------------------------------


def test_fused_sparse_registry_counters_and_journal(tmp_path):
    REGISTRY.reset()
    users, items, ts = _wide_row_stream()
    jpath = tmp_path / "journal.jsonl"
    _run_sparse(users, items, ts, user_cut=6, fused_window="on",
                journal=str(jpath))
    fused_total = REGISTRY.gauge("cooc_fused_dispatches_total").get()
    chained_total = REGISTRY.gauge("cooc_chained_dispatches_total").get()
    assert fused_total > 0, "no window ever took the fused sparse path"
    # Per-bucket shape specialization is visible and bounded.
    compiles = REGISTRY.gauge("cooc_fused_bucket_compilations_total").get()
    assert 0 < compiles <= fused_total + 4
    from tpu_cooccurrence.observability.journal import (read_records,
                                                        validate_record)

    recs = [r for r in read_records(str(jpath)) if "seq" in r]
    for r in recs:
        validate_record(r)
    flags = [r["fused"] for r in recs]
    assert set(flags) <= {0, 1}
    assert flags.count(1) == fused_total
    # The wall-time split histograms bucketed the same windows (the
    # chained bucket additionally absorbs dispatch-free empty windows,
    # which never increment the dispatch gauge).
    assert (REGISTRY.histogram("cooc_window_score_seconds_fused").count
            == fused_total)
    assert (REGISTRY.histogram("cooc_window_score_seconds_chained").count
            >= chained_total)


def test_fused_sparse_uplink_is_ledger_booked(tmp_path):
    """The fused dispatch's uplink (packed words + registry delta +
    score rows) books on the TransferLedger like every other upload —
    the journal's per-window wire delta stays exact."""
    users, items, ts = _wide_row_stream()
    jpath = tmp_path / "journal.jsonl"
    _run_sparse(users, items, ts, user_cut=6, fused_window="on",
                wire_format="packed", journal=str(jpath))
    recs = [json.loads(line) for line in open(jpath)]
    fused_recs = [r for r in recs if r.get("fused") == 1 and r.get("pairs")]
    assert fused_recs
    for r in fused_recs:
        assert r["wire"]["h2d_bytes"] > 0
        # Packed wire: the encoded-uplink pair is accounted per window.
        assert r["wire"]["uplink_enc_bytes"] > 0
        assert (r["wire"]["uplink_raw_bytes"]
                >= r["wire"]["uplink_enc_bytes"])


# -- config validation --------------------------------------------------


def test_fused_sparse_config_validation():
    # Single-process sparse accepts a forced 'on'.
    Config(window_size=10, backend=Backend.SPARSE, fused_window="on")
    # Sharded sparse now accepts it too (PR 16: one launch per worker).
    Config(window_size=10, backend=Backend.SPARSE, num_shards=2,
           fused_window="on")
    # ... but per-window result streaming still cannot fuse, on any
    # topology (the fused program scatters results on device).
    with pytest.raises(ValueError, match="deferred results"):
        Config(window_size=10, backend=Backend.SPARSE, emit_updates=True,
               fused_window="on")
    with pytest.raises(ValueError, match="deferred results"):
        Config(window_size=10, backend=Backend.SPARSE, num_shards=2,
               emit_updates=True, fused_window="on")
    # Hybrid's sparse half stays single-process fused only.
    with pytest.raises(ValueError, match="single-process"):
        Config(window_size=10, backend=Backend.HYBRID, num_shards=2,
               item_cut=100, fused_window="on")
    # Oracle stays chained-only.
    with pytest.raises(ValueError, match="device or sparse"):
        Config(window_size=10, backend=Backend.ORACLE, fused_window="on")


def test_fused_sparse_emit_updates_auto_degrades_chained():
    """'auto'/'on'+streaming cannot fuse; with auto the scorer quietly
    stays chained (defer-only contract) and results are unchanged."""
    users, items, ts = _ladder_edge_stream()
    kw = dict(user_cut=4, item_cut=500, emit_updates=True)
    REGISTRY.reset()
    chained = _run_sparse(users, items, ts, fused_window="off", **kw)
    auto = _run_sparse(users, items, ts, fused_window="auto", **kw)
    assert _table(chained) == _table(auto)
    assert REGISTRY.gauge("cooc_fused_dispatches_total").get() == 0
