"""Real-dataset adapters against checked-in fixture slices.

Every ML-25M / Instacart number so far ran on shape-matched stand-ins;
these tests make the REAL loaders (`movielens_interactions`,
`instacart_interactions`) and the env-path selection in bench/configs
run in CI on tiny checked-in slices, so a dataset drop-in cannot fail
for the first time inside a scarce TPU grant window (VERDICT r3,
Next #4). Reference ingest/parse: FlinkCooccurrences.java:207-219.
"""

import os

import numpy as np
import pytest

from tpu_cooccurrence.config import Backend, Config
from tpu_cooccurrence.io.synthetic import (instacart_interactions,
                                           movielens_interactions)
from tpu_cooccurrence.job import CooccurrenceJob

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fixtures")
RATINGS = os.path.join(FIXTURES, "ratings.csv")
UDATA = os.path.join(FIXTURES, "u.data")
ORDERS = os.path.join(FIXTURES, "orders.csv")
ORDER_PRODUCTS = os.path.join(FIXTURES, "order_products.csv")


def test_movielens_25m_csv_format():
    (users, items, ts), = movielens_interactions(RATINGS)
    assert len(users) == 40
    # Sorted by timestamp, seconds -> ms.
    assert (np.diff(ts) >= 0).all()
    assert ts.min() == 1141415790 * 1000
    # The earliest event is user 2 rating movie 318.
    assert users[0] == 2 and items[0] == 318


def test_movielens_min_rating_filter():
    (users, items, _ts), = movielens_interactions(RATINGS, min_rating=1.0)
    # Two 0.5-star rows (user 2 x 110, user 4 x 318) drop out.
    assert len(users) == 38
    pairs = set(zip(users.tolist(), items.tolist()))
    assert (2, 110) not in pairs and (4, 318) not in pairs


def test_movielens_100k_udata_format():
    (users, items, ts), = movielens_interactions(UDATA)
    assert len(users) == 30
    assert (np.diff(ts) >= 0).all()
    assert ts[0] == 874833878 * 1000   # user 291, item 118
    assert users[0] == 291 and items[0] == 118


def test_instacart_join_and_order():
    (users, items, ts), = instacart_interactions(ORDERS, ORDER_PRODUCTS)
    assert len(users) == 26
    assert (np.diff(ts) >= 0).all()   # ordered by order_number
    # Product 43633 appears only in order 3367565 -> user 2, order_number 3.
    mask = items == 43633
    assert users[mask].tolist() == [2] and ts[mask].tolist() == [3]
    # user 1's first basket holds products 196, 14084, 12427 at ts 1.
    first = items[(users == 1) & (ts == 1)]
    assert set(first.tolist()) == {196, 14084, 12427}


@pytest.mark.parametrize("loader,args", [
    (movielens_interactions, (RATINGS,)),
    (movielens_interactions, (UDATA,)),
    (instacart_interactions, (ORDERS, ORDER_PRODUCTS)),
])
def test_adapters_end_to_end_through_job(loader, args):
    """The real-loader output drives a full job to results (the id space
    is raw dataset ids — the job's vocab layer maps them)."""
    (users, items, ts), = loader(*args)
    cfg = Config(window_size=10_000_000, seed=0xC0FFEE, item_cut=500,
                 user_cut=500, backend=Backend.ORACLE)
    job = CooccurrenceJob(cfg)
    job.add_batch(users, items, ts)
    job.finish()
    assert job.latest, "fixture stream produced no recommendations"
    assert job.windows_fired > 0


def test_bench_env_path_selection(monkeypatch):
    """bench/configs picks the real dataset exactly when the env points
    at an existing file, and reports synthetic_standin accordingly."""
    from tpu_cooccurrence.bench import configs

    monkeypatch.setenv("MOVIELENS_100K", UDATA)
    users, items, ts, model = configs._movielens_100k()
    assert model is None and len(users) == 30

    monkeypatch.setenv("MOVIELENS_25M", RATINGS)
    users, items, ts, model = configs._movielens_25m(limit=20)
    assert model is None and len(users) == 20

    monkeypatch.setenv("INSTACART_ORDERS", ORDERS)
    monkeypatch.setenv("INSTACART_ORDER_PRODUCTS", ORDER_PRODUCTS)
    users, items, ts, model = configs._instacart()
    assert model is None and len(users) == 26

    # Missing path -> stand-in, labeled with the generator model (the
    # helper that picks the generator owns the provenance label).
    monkeypatch.setenv("MOVIELENS_100K", "/nonexistent/u.data")
    *_ignore, model = configs._movielens_100k()
    assert model == "calibrated-v1"


def test_bench_config_runs_real_fixture(monkeypatch):
    """A whole benchmark config on the real loader path: the BenchResult
    must carry synthetic_standin=False."""
    from tpu_cooccurrence.bench import configs

    monkeypatch.setenv("MOVIELENS_100K", UDATA)
    res = configs.config2_ml100k(backend=Backend.ORACLE)
    d = res.as_dict()
    assert d["synthetic_standin"] is False
    assert d["pairs"] >= 0
