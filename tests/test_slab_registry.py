"""SlabIndex row registries (dense vs SMASH-style bitmap) and the
allocator/compaction edge cases the move plan can hit.

Covers the PR-7 satellite checklist explicitly: row relocation across a
registry capacity doubling, zero-length rows, re-insertion of a key
whose row was freed (promotion) and its region reclaimed by compaction —
plus the bitmap registry's RSS claim at a 1M-row space and dense/bitmap
behavioral equivalence under fuzz.
"""

import numpy as np
import pytest

from tpu_cooccurrence.state.sparse_scorer import (
    BitmapRowRegistry, DenseRowRegistry, SlabCapacityError, SlabIndex,
    _pow2ceil, make_row_registry)

REGS = ["dense", "bitmap"]


@pytest.mark.parametrize("kind", REGS)
def test_registry_get_update_clear(kind):
    reg = make_row_registry(64, kind)
    rows = np.asarray([3, 70, 1000], np.int64)
    s0, l0, c0 = reg.get(rows)  # absent rows read as zeros
    assert not s0.any() and not l0.any() and not c0.any()
    reg.update(rows, start=np.asarray([10, 20, 30], np.int32),
               length=np.asarray([1, 2, 3], np.int32),
               cap=np.asarray([4, 4, 4], np.int32))
    s, ln, c = reg.get(rows)
    np.testing.assert_array_equal(s, [10, 20, 30])
    np.testing.assert_array_equal(ln, [1, 2, 3])
    np.testing.assert_array_equal(c, [4, 4, 4])
    np.testing.assert_array_equal(reg.occupied(), rows)
    # Scattered single-field update keeps the others.
    reg.update(np.asarray([70]), length=np.asarray([9], np.int32))
    s, ln, c = reg.get(np.asarray([70]))
    assert (int(s[0]), int(ln[0]), int(c[0])) == (20, 9, 4)
    reg.clear(np.asarray([70]))
    assert 70 not in reg.occupied().tolist()
    s, ln, c = reg.get(rows)
    np.testing.assert_array_equal(ln, [1, 0, 3])


def test_bitmap_registry_matches_dense_under_fuzz():
    rng = np.random.default_rng(0xBEE)
    a = make_row_registry(64, "dense")
    b = make_row_registry(64, "bitmap")
    universe = 5000
    for step in range(200):
        rows = np.unique(rng.integers(0, universe, rng.integers(1, 40)))
        field = rng.integers(0, 3)
        vals = rng.integers(1, 1000, len(rows)).astype(np.int32)
        kw = [{"start": vals}, {"length": vals}, {"cap": vals}][field]
        a.ensure(int(rows.max()))
        a.update(rows, **kw)
        b.update(rows, **kw)
        probe = np.unique(rng.integers(0, universe, 64))
        for x, y in zip(a.get(probe), b.get(probe)):
            np.testing.assert_array_equal(x, y)
        if step % 17 == 0:
            victims = np.unique(rng.integers(0, universe, 5))
            a.clear(victims)
            b.clear(victims)
    np.testing.assert_array_equal(a.occupied(), b.occupied())


def test_bitmap_registry_rss_claim():
    """The tentpole's memory claim, pinned: at a 1M-row space with a
    sparse occupancy the bitmap+rank layout is at least 4x smaller than
    the dense triple."""
    n_rows = 1 << 20
    occupied = np.arange(0, n_rows, 11, dtype=np.int64)[:100_000]
    dense = DenseRowRegistry(n_rows)
    bitmap = BitmapRowRegistry(n_rows)
    vals = np.ones(len(occupied), np.int32)
    for reg in (dense, bitmap):
        reg.update(occupied, start=vals, length=vals, cap=vals)
    assert dense.nbytes >= 12 * n_rows
    assert bitmap.nbytes * 4 < dense.nbytes
    # Same answers, an order of magnitude less host RSS.
    probe = np.asarray([0, 11, 5, n_rows - 1], np.int64)
    for x, y in zip(dense.get(probe), bitmap.get(probe)):
        np.testing.assert_array_equal(x, y)


@pytest.mark.parametrize("kind", REGS)
def test_relocation_across_registry_capacity_doubling(kind):
    """Satellite: a row relocated in the same apply() that doubles the
    row-registry capacity (high row id arrives together with growth of a
    low row) must keep slots/moves consistent."""
    idx = SlabIndex(rows_capacity=64, row_index=kind)
    base = np.asarray([(0 << 32) | d for d in range(4)], np.int64)
    p0 = idx.apply(base)
    assert p0.mv is None  # fresh row: nothing to move
    # Row 0 outgrows cap 4 in the same window that first touches a row
    # beyond the registry capacity (forces ensure/doubling mid-apply).
    big_row = 70_000
    batch = np.unique(np.concatenate([
        (0 << 32) + np.arange(4, 9),
        ((big_row << 32) + np.arange(3)).astype(np.int64)]))
    p1 = idx.apply(batch)
    assert idx.rows_cap > 64
    assert p1.mv is not None  # row 0 relocated
    old0, new0, len0 = (int(p1.mv[0, 0]), int(p1.mv[1, 0]),
                        int(p1.mv[2, 0]))
    assert (old0, len0) == (int(p0.slots[0]), 4)
    # Index agrees with the relocated layout for ALL of row 0's cells.
    keys, slots = idx.row_cells(np.asarray([0], np.int64))
    assert len(keys) == 9
    assert slots.min() >= new0
    s, ln, c = idx.rows.get(np.asarray([0, big_row], np.int64))
    assert int(ln[0]) == 9 and int(ln[1]) == 3
    assert int(c[0]) >= 9


@pytest.mark.parametrize("kind", REGS)
def test_zero_length_rows_ignored_everywhere(kind):
    """Satellite: rows that exist in the row space but never held a cell
    read as (0, 0, 0), never enter compaction, and never appear in
    row_cells output."""
    idx = SlabIndex(rows_capacity=64, row_index=kind)
    idx.apply(np.asarray([(5 << 32) | 1, (9 << 32) | 2], np.int64))
    ghost = np.asarray([0, 4, 63], np.int64)
    s, ln, c = idx.rows.get(ghost)
    assert not s.any() and not ln.any() and not c.any()
    keys, slots = idx.row_cells(ghost)
    assert len(keys) == 0 and len(slots) == 0
    assert sorted(idx.rows.occupied().tolist()) == [5, 9]
    gmap = idx.compact()
    assert idx.heap_end == len(gmap)


@pytest.mark.parametrize("kind", REGS)
def test_reinsert_key_freed_by_compaction(kind):
    """Satellite: free a row (promotion), let compaction reclaim its
    region, then re-insert the SAME key — it must allocate a fresh slot
    and the index must treat it as new."""
    idx = SlabIndex(rows_capacity=64, row_index=kind)
    key_a = np.asarray([(1 << 32) | 7, (1 << 32) | 8], np.int64)
    key_b = np.asarray([(2 << 32) | d for d in range(6)], np.int64)
    idx.apply(key_a)
    idx.apply(key_b)
    idx.free_rows(np.asarray([1], np.int64))
    assert idx.garbage > 0
    gmap = idx.compact()  # reclaims row 1's region
    assert idx.garbage == 0
    assert 1 not in idx.rows.occupied().tolist()
    # Row 2 survived compaction intact.
    s2, l2, _ = idx.rows.get(np.asarray([2], np.int64))
    assert int(l2[0]) == 6
    assert len(gmap) == idx.heap_end
    # Re-insert the freed key: allocated as NEW, fresh slot, correct len.
    plan = idx.apply(key_a[:1].copy())
    assert plan.new_sel.all()
    s1, l1, c1 = idx.rows.get(np.asarray([1], np.int64))
    assert int(l1[0]) == 1 and int(c1[0]) >= 1
    assert int(plan.slots[0]) == int(s1[0])


@pytest.mark.parametrize("kind", REGS)
def test_registry_choice_is_behavior_invariant_for_allocator(kind):
    """Whole-allocator fuzz under each registry: same plans as the
    reference (dense) run, window for window."""
    rng = np.random.default_rng(0xF00D)
    ref = SlabIndex(rows_capacity=8, row_index="dense")
    alt = SlabIndex(rows_capacity=8, row_index=kind)
    for _ in range(40):
        n = int(rng.integers(1, 100))
        rows = rng.integers(0, 60, n).astype(np.int64)
        dsts = rng.integers(0, 200, n)
        d_key = np.unique((rows << 32) | dsts)
        pa, pb = ref.apply(d_key.copy()), alt.apply(d_key.copy())
        np.testing.assert_array_equal(pa.slots, pb.slots)
        np.testing.assert_array_equal(pa.new_sel, pb.new_sel)
        if pa.mv is not None or pb.mv is not None:
            np.testing.assert_array_equal(pa.mv, pb.mv)
        if ref.needs_compaction(64):
            np.testing.assert_array_equal(ref.compact(), alt.compact())


def test_pow2ceil_overflow_guard():
    """Satellite: capacity growth crossing 2^31 cells fails loudly with
    the permanent-exit config error instead of wrapping to a negative
    int32 capacity."""
    assert int(_pow2ceil(np.asarray([3]), 4)[0]) == 4
    with pytest.raises(SlabCapacityError, match="int32"):
        _pow2ceil(np.asarray([2**31 - 5]), 4)


def test_allocate_heap_overflow_guard():
    idx = SlabIndex(rows_capacity=64)
    idx.heap_end = 2**31 - 2
    with pytest.raises(SlabCapacityError, match="heap growth"):
        idx.apply(np.asarray([(3 << 32) | 1], np.int64))


def test_make_row_registry_env(monkeypatch):
    monkeypatch.setenv("TPU_COOC_ROW_INDEX", "dense")
    assert make_row_registry(64).kind == "dense"
    monkeypatch.setenv("TPU_COOC_ROW_INDEX", "bitmap")
    assert make_row_registry(64).kind == "bitmap"
    monkeypatch.setenv("TPU_COOC_ROW_INDEX", "nope")
    with pytest.raises(ValueError, match="TPU_COOC_ROW_INDEX"):
        make_row_registry(64)
