"""Serving fleet read replicas (ISSUE 13).

The contracts under test:

* **Snapshot parity (the acceptance bar)** — after tailing the delta
  log to generation G, a replica's ``TopKSnapshot`` rows are
  BIT-IDENTICAL to the ingest process's snapshot at G, compared
  restored-vs-restored (the only bit-exact sparse comparator: both
  sides rebuild from the checkpointed float64 arrays through the same
  float32 packing).
* **Consumer semantics of the delta log** — an orphan delta (delta
  file present, generation npz missing) is never consumed; a
  ``DeltaCorrupt`` mid-tail drives the documented checkpoint-resync
  fallback (and the replica NEVER renames the writer's files — it is a
  read-only consumer); a full generation interposed in the log
  (compaction) re-bootstraps instead of wedging.
* **Read-your-window consistency** — every ``/recommend`` response
  carries the delta-log ``generation`` tag, and ``min_gen`` answers
  503 while the replica lags the client's last-seen generation.
* **Observability** — the ``cooc_replica_generation_lag`` gauge, the
  lag block on the replica's ``/healthz``, and one validated
  ``replica`` journal record per replayed generation.
* **Fleet robustness (slow)** — kill one replica mid-storm: zero
  failed queries after the drain, and the supervisor's relaunched
  replica re-syncs from checkpoint + delta tail to the live
  generation, with no writer involvement.
"""

import json
import os
import shutil
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from tpu_cooccurrence.config import Backend, Config
from tpu_cooccurrence.job import CooccurrenceJob
from tpu_cooccurrence.observability.journal import (
    read_records,
    validate_record,
)
from tpu_cooccurrence.observability.registry import REGISTRY
from tpu_cooccurrence.serving.recommend import UserHistory
from tpu_cooccurrence.serving.replica import ReadReplica, ReplicaServer
from tpu_cooccurrence.serving.snapshot import SnapshotBuilder
from tpu_cooccurrence.state import checkpoint as ckpt
from tpu_cooccurrence.state import delta as deltalog
from tpu_cooccurrence.state.results import TopKBatch

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _reset_registry():
    REGISTRY.reset()
    yield


def _writer_cfg(d, **kw):
    kw.setdefault("backend", Backend.SPARSE)
    kw.setdefault("window_size", 10)
    kw.setdefault("seed", 0xABCD)
    kw.setdefault("item_cut", 5)
    kw.setdefault("user_cut", 3)
    kw.setdefault("checkpoint_every_windows", 2)
    kw.setdefault("checkpoint_retain", 100)
    kw.setdefault("checkpoint_incremental", True)
    kw.setdefault("serve_port", 0)
    # A pure delta chain (no ratio-triggered compaction): the tail and
    # journal assertions below need a deterministic unbroken chain; the
    # compaction/full-generation gap paths are constructed explicitly
    # in their own tests.
    kw.setdefault("checkpoint_compact_ratio", 100.0)
    return Config(checkpoint_dir=d, **kw)


@pytest.fixture(scope="module")
def writer_repo(tmp_path_factory):
    """One ingest run shared by every read-side test in this file:
    live checkpoint+delta directory, plus a copy taken at the halfway
    checkpoint (the replica's early-bootstrap origin)."""
    root = tmp_path_factory.mktemp("replica")
    d = str(root / "state")
    rng = np.random.default_rng(7)
    n = 1600
    users = rng.integers(0, 25, n).astype(np.int64)
    items = rng.integers(100, 180, n).astype(np.int64)
    ts = np.cumsum(rng.integers(0, 2, n)).astype(np.int64)
    job = CooccurrenceJob(_writer_cfg(d))
    half = 700
    for lo in range(0, half, 97):
        job.add_batch(users[lo:lo + 97], items[lo:lo + 97],
                      ts[lo:lo + 97])
    job.checkpoint()
    early = str(root / "state-early")
    shutil.copytree(d, early)
    for lo in range(half, n, 97):
        job.add_batch(users[lo:lo + 97], items[lo:lo + 97],
                      ts[lo:lo + 97])
    job.finish()
    return {"live": d, "early": early, "users": users}


def _tailed_replica(writer_repo, tmp_path, journal=None):
    """A replica bootstrapped from the EARLY copy, then tailed over the
    live directory to the newest generation."""
    rep = ReadReplica(writer_repo["early"], journal=journal)
    g0 = rep.bootstrap()
    rep.state_dir = writer_repo["live"]
    applied = rep.poll()
    return rep, g0, applied


def _restored_writer_snapshot(writer_repo):
    """The restored-vs-restored comparator's writer side: a fresh job
    restored from the live directory, serving snapshot seeded from the
    checkpointed results."""
    job = CooccurrenceJob(_writer_cfg(writer_repo["live"],
                                      checkpoint_every_windows=0))
    job.restore()
    return job


# -- snapshot parity (the acceptance bar) -------------------------------


def test_replica_snapshot_parity_restored_vs_restored(writer_repo,
                                                      tmp_path):
    rep, g0, applied = _tailed_replica(writer_repo, tmp_path)
    live_gen = ckpt.generations(writer_repo["live"], "")[0][0]
    assert applied > 0 and rep.generation == live_gen > g0
    jr = _restored_writer_snapshot(writer_repo)
    snap_w = jr.serving.builder.current
    snap_r = rep.plane.builder.current
    # The replica reconstructed the WRITER's dense id space exactly.
    np.testing.assert_array_equal(jr.item_vocab.external_array(),
                                  rep.item_vocab.external_array())
    assert snap_w.rows == snap_r.rows > 0
    # Row-for-row bit identity: membership, partner ids, float32 scores.
    rows_checked = 0
    for dense in range(len(jr.item_vocab)):
        rw, rr = snap_w.row(dense), snap_r.row(dense)
        assert (rw is None) == (rr is None)
        if rw is None:
            continue
        np.testing.assert_array_equal(rw[0], rr[0])
        np.testing.assert_array_equal(rw[1], rr[1])
        assert rr[1].dtype == np.float32
        rows_checked += 1
    assert rows_checked == snap_w.rows
    # The replica's snapshot is tagged with the LOG position, not the
    # content counter.
    assert snap_r.generation == live_gen


def test_mid_stream_gap_rebootstraps_not_resyncs(writer_repo, tmp_path):
    """A delta whose predecessor the replica never saw (the shape a
    compaction or retention leaves behind) re-bootstraps from the
    checkpoint — the resyncs counter (which means corruption) stays
    untouched."""
    d = str(tmp_path / "gap")
    shutil.copytree(writer_repo["live"], d)
    rep = ReadReplica(writer_repo["early"])
    g0 = rep.bootstrap()
    rep.state_dir = d
    top = ckpt.generations(d, "")[0][0]
    # The writer compacts (full base, no delta) ...
    w = CooccurrenceJob(_writer_cfg(d, checkpoint_every_windows=0,
                                    checkpoint_incremental=False))
    w.restore()
    w.checkpoint()
    # ... then keeps streaming deltas chained from the base.
    w2 = CooccurrenceJob(_writer_cfg(d, checkpoint_every_windows=0))
    w2.restore()
    t0 = int(w2.engine.max_ts_seen) + 100
    w2.add_batch(np.asarray([1, 2]), np.asarray([101, 102]),
                 np.asarray([t0, t0 + 1]))
    w2.checkpoint()
    newest = ckpt.generations(d, "")[0][0]
    assert newest == top + 2
    assert newest in deltalog.delta_generations(d, "")  # a delta ...
    assert top + 1 not in deltalog.delta_generations(d, "")  # ... past
    # a full base the replica never saw: the in-stream gap.
    applied = rep.poll()
    assert applied > 0
    assert rep.generation == newest
    assert rep.resyncs == 0
    assert rep.lag() == 0


def test_trailing_full_generation_rebootstraps(writer_repo, tmp_path):
    """A FULL base at the TIP of the log (a compaction with no delta
    after it yet) must not wedge the replica one generation behind:
    poll re-bootstraps to it."""
    d = str(tmp_path / "trail")
    shutil.copytree(writer_repo["live"], d)
    rep = ReadReplica(d)
    top = rep.bootstrap()
    # The writer compacts: a restored job commits one more FULL
    # generation (no delta file) at the tip.
    w = CooccurrenceJob(_writer_cfg(d, checkpoint_every_windows=0,
                                    checkpoint_incremental=False))
    w.restore()
    w.checkpoint()
    newest = ckpt.generations(d, "")[0][0]
    assert newest == top + 1
    assert newest not in deltalog.delta_generations(d, "")
    applied = rep.poll()
    assert applied > 0
    assert rep.generation == newest
    assert rep.resyncs == 0


# -- delta-log consumer semantics ---------------------------------------


def test_orphan_delta_is_never_consumed(writer_repo, tmp_path):
    """A delta file without its generation npz (the crashed-save shape)
    must never advance the replica — the writer may rewrite it with
    different content on restart."""
    d = str(tmp_path / "orphan")
    shutil.copytree(writer_repo["live"], d)
    top = ckpt.generations(d, "")[0][0]
    some_delta = deltalog.delta_path(
        d, "", deltalog.delta_generations(d, "")[-1])
    orphan = deltalog.delta_path(d, "", top + 3)
    shutil.copyfile(some_delta, orphan)
    rep = ReadReplica(d)
    rep.bootstrap()
    applied = rep.poll()
    assert applied == 0
    assert rep.generation == top  # never walked into the orphan
    # The orphan does not even count toward lag (npz-gated newest).
    assert rep.lag() == 0


def test_delta_corrupt_mid_tail_drives_checkpoint_resync(writer_repo,
                                                         tmp_path):
    """The documented consumer loop: DeltaCorrupt while tailing ->
    resync from the newest VERIFYING checkpoint (exactly like restore's
    fallback walk) — and the replica, a read-only consumer, never
    quarantines or renames the writer's files."""
    d = str(tmp_path / "corrupt")
    shutil.copytree(writer_repo["live"], d)
    rep = ReadReplica(d)
    rep.bootstrap()
    g_at = rep.generation
    # Rewind the replica, then corrupt the first delta it will re-read.
    chain_base, chain = ckpt.chain_of(d, "", g_at)
    if not chain:
        pytest.skip("newest generation is a full base on this stream")
    rep.generation = chain[0] - 1
    victim = deltalog.delta_path(d, "", chain[0])
    raw = open(victim, "rb").read()
    with open(victim, "wb") as f:
        f.write(raw[: len(raw) // 2])
    applied = rep.poll()
    assert applied > 0
    assert rep.resyncs == 1
    # Resynced to the newest generation whose WHOLE chain verifies: the
    # corrupt link poisons everything chained above it.
    assert rep.generation == chain_base
    assert REGISTRY.gauge("cooc_replica_resyncs_total").get() == 1
    # Read-only contract: the corrupt delta is still in place, and no
    # *.corrupt / *.partial quarantine file appeared.
    assert os.path.exists(victim)
    assert not [n for n in os.listdir(d)
                if n.endswith((".corrupt", ".partial"))]
    # Serving survives the resync (older but internally consistent).
    items, snap, _fb = rep.query(None, 5)
    assert snap.generation == chain_base


def test_retention_race_keeps_serving(writer_repo, tmp_path):
    """Mid-service re-bootstrap racing the writer's retention: if no
    generation is restorable at that instant, the replica keeps
    serving its current (older, consistent) snapshot and retries next
    poll — it must not die with CheckpointCorrupt."""
    d = str(tmp_path / "race")
    shutil.copytree(writer_repo["live"], d)
    rep = ReadReplica(writer_repo["early"])
    g0 = rep.bootstrap()
    rows0 = rep.rows
    rep.state_dir = d
    # The writer "retired" everything except the newest npz+delta pair,
    # whose chain is now unresolvable (its base is gone): a gap the
    # re-bootstrap cannot restore from, transiently.
    top = ckpt.generations(d, "")[0][0]
    for g, path in ckpt.generations(d, ""):
        if g < top:
            os.remove(path)
    for g in deltalog.delta_generations(d, ""):
        if g < top:
            os.remove(deltalog.delta_path(d, "", g))
    applied = rep.poll()  # must not raise
    assert applied == 0
    assert rep.generation == g0  # still serving the old generation
    items, snap, _fb = rep.query(None, 5)
    assert snap.generation == g0 and rep.rows == rows0


def test_foreign_topk_record_is_delta_corrupt(writer_repo, tmp_path):
    """A top-K record referencing items outside the replayed vocab
    chain must resync, never silently diverge the dense id space."""
    rep = ReadReplica(writer_repo["early"])
    rep.bootstrap()
    with pytest.raises(deltalog.DeltaCorrupt):
        rep._pack_external(rep.item_vocab,
                           np.asarray([10 ** 12]),  # never mapped
                           np.asarray([1]), np.asarray([10 ** 12 + 1]),
                           np.asarray([1.0]))


# -- observability: lag gauge, healthz block, journal record ------------


def test_lag_gauge_healthz_and_journal_records(writer_repo, tmp_path):
    jp = str(tmp_path / "replica.jsonl")
    rep = ReadReplica(writer_repo["early"], journal=jp)
    rep.bootstrap()
    rep.state_dir = writer_repo["live"]
    live_gen = ckpt.generations(writer_repo["live"], "")[0][0]
    # Before the first poll the replica lags the live directory.
    assert rep.lag() == live_gen - rep.generation > 0
    rep._refresh_lag()
    assert REGISTRY.gauge("cooc_replica_generation_lag").get() \
        == rep.lag()
    rep.poll()
    assert REGISTRY.gauge("cooc_replica_generation_lag").get() == 0
    assert REGISTRY.gauge("cooc_replica_generation").get() == live_gen
    # One validated journal record per replayed delta generation, with
    # a monotone generation column.
    recs = [r for r in read_records(jp) if "replica" in r]
    assert recs, "no replica journal records written"
    for r in recs:
        validate_record(r)
    gens = [r["replica"] for r in recs]
    assert gens == sorted(gens)
    assert all(r["resyncs"] == 0 for r in recs)
    # The /healthz lag block.
    srv = ReplicaServer(REGISTRY, rep, port=0).start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/healthz") as resp:
            h = json.load(resp)
        assert h["status"] == "ok"
        blk = h["replica"]
        assert blk["generation"] == live_gen
        assert blk["newest_generation"] == live_gen
        assert blk["lag"] == 0
        assert blk["resyncs"] == 0
        assert blk["deltas_applied"] == rep.deltas_applied
        assert h["snapshot_generation"] == live_gen
    finally:
        srv.stop()
    rep.close()


def test_replica_stale_healthz_drains(writer_repo):
    """A wedged tail loop (no poll) reports replica_stale + 503."""
    rep = ReadReplica(writer_repo["live"])
    rep.bootstrap()
    rep.last_poll_unix = time.time() - 3600
    srv = ReplicaServer(REGISTRY, rep, port=0,
                        stale_after_s=1.0).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/healthz")
        assert ei.value.code == 503
        assert json.load(ei.value)["status"] == "replica_stale"
    finally:
        srv.stop()


# -- read-your-window: the generation tag + min_gen gate ----------------


def test_recommend_carries_generation_and_min_gen_gate(writer_repo,
                                                       tmp_path):
    rep, _g0, _ = _tailed_replica(writer_repo, tmp_path)
    srv = ReplicaServer(REGISTRY, rep, port=0).start()
    base = f"http://127.0.0.1:{srv.port}"
    try:
        with urllib.request.urlopen(
                f"{base}/recommend?user=3&n=5") as resp:
            body = json.load(resp)
        assert body["generation"] == rep.generation
        # Satisfied gate: the client's last-seen generation is served.
        with urllib.request.urlopen(
                f"{base}/recommend?user=3&n=5"
                f"&min_gen={rep.generation}") as resp:
            assert json.load(resp)["generation"] >= rep.generation
        # Lagging replica: 503 with the routing fields.
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"{base}/recommend?user=3&n=5"
                f"&min_gen={rep.generation + 7}")
        assert ei.value.code == 503
        err = json.load(ei.value)
        assert err["generation"] == rep.generation
        assert err["min_gen"] == rep.generation + 7
        # Garbage min_gen is a 400, not a crash.
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"{base}/recommend?user=3&n=5&min_gen=banana")
        assert ei.value.code == 400
    finally:
        srv.stop()


def test_history_replay_personalizes_known_users(writer_repo, tmp_path):
    """The delta log's reservoir records give replicas per-user
    history: a user the writer sampled gets the BLEND path (not the
    popularity fallback)."""
    rep, _g0, _ = _tailed_replica(writer_repo, tmp_path)
    blended = 0
    for u in np.unique(writer_repo["users"])[:10].tolist():
        items, _snap, fallback = rep.query(int(u), 5)
        if not fallback and items:
            blended += 1
    assert blended > 0, "no sampled user got a personalized blend"
    # An unknown user still answers (popularity fallback).
    items, _snap, fallback = rep.query(10 ** 9, 5)
    assert fallback and items


# -- unit surfaces ------------------------------------------------------


def test_user_history_set_rows_clamps_and_overwrites():
    h = UserHistory(length=4)
    users = np.asarray([2, 5])
    lens = np.asarray([2, 6])  # 6 > ring length: keep first 4
    flat = np.asarray([10, 11, 20, 21, 22, 23, 24, 25])
    h.set_rows(users, lens, flat)
    out = np.zeros(4, dtype=np.int64)
    assert h.recent(2, out) == 2 and list(out[:2]) == [10, 11]
    assert h.recent(5, out) == 4 and list(out) == [20, 21, 22, 23]
    # A later set REPLACES the row (replica replay is a set, not an
    # append).
    h.set_rows(np.asarray([5]), np.asarray([1]), np.asarray([99]))
    assert h.recent(5, out) == 1 and out[0] == 99


def test_publish_with_explicit_generation_tags_and_retags():
    class _Vocab:
        def __len__(self):
            return 8

        def external_array(self):
            return np.arange(8, dtype=np.int64)

    b = SnapshotBuilder(_Vocab())
    b.absorb(TopKBatch(np.asarray([1], np.int32),
                       np.asarray([[2]], np.int32),
                       np.asarray([[1.5]], np.float32)))
    snap = b.publish(generation=17)
    assert snap.generation == 17
    # Quiet publish with a newer tag: same object, advanced tag
    # (content at G == content at G-1 when the delta was empty).
    snap2 = b.publish(generation=19)
    assert snap2 is snap and snap.generation == 19
    # Quiet publish without a tag keeps everything.
    assert b.publish().generation == 19
    # Dirty publish without a tag resumes the content counter.
    b.absorb(TopKBatch(np.asarray([2], np.int32),
                       np.asarray([[3]], np.int32),
                       np.asarray([[1.0]], np.float32)))
    assert b.publish().generation == 20


def test_fleet_child_argv_strips_and_suffixes():
    from tpu_cooccurrence.serving.replica import _fleet_child_argv

    raw = ["--state-dir", "D", "--fleet", "3", "--fleet-dir", "F",
           "--journal", "J.jsonl", "--run-seconds", "30", "--port=5"]
    out = _fleet_child_argv(raw, "F", 1)
    assert "--fleet" not in out and "--fleet-dir" not in out
    assert "--port=5" not in out
    # Per-process journal: two replicas must not interleave one file.
    assert out[out.index("--journal") + 1] == "J.jsonl.p1"
    assert out[out.index("--process-id") + 1] == "1"
    assert out[out.index("--port-file") + 1].endswith("replica.p1.port")
    out2 = _fleet_child_argv(["--state-dir", "D", "--journal=J.jsonl"],
                             "F", 0)
    assert "--journal=J.jsonl.p0" in out2


# -- the fleet (subprocess surfaces; slow lane per the tier-1 budget) ---


def _spawn_writer_dir(tmp_path, n=1200):
    d = str(tmp_path / "state")
    rng = np.random.default_rng(7)
    users = rng.integers(0, 25, n).astype(np.int64)
    items = rng.integers(100, 160, n).astype(np.int64)
    ts = np.cumsum(rng.integers(0, 2, n)).astype(np.int64)
    job = CooccurrenceJob(_writer_cfg(d, serve_port=None))
    half = n // 2
    for lo in range(0, half, 97):
        job.add_batch(users[lo:lo + 97], items[lo:lo + 97],
                      ts[lo:lo + 97])
    job.checkpoint()
    return d, job, (users, items, ts), half


def _wait_port(path, timeout=90):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        try:
            with open(path) as f:
                info = json.load(f)
            urllib.request.urlopen(info["url"] + "/healthz", timeout=2)
            return info
        except Exception:
            time.sleep(0.25)
    raise TimeoutError(path)


@pytest.mark.slow
def test_cooc_replica_cli_serves_and_exits(tmp_path):
    """The cooc-replica entrypoint: bootstrap, port file, tagged
    /recommend, clean exit at --run-seconds. Slow lane: a subprocess
    interpreter + a --run-seconds serve window (the tier-1 870s budget
    is already tight; the in-process tests above cover the replica
    logic, this pins the packaging)."""
    d, job, (users, items, ts), half = _spawn_writer_dir(tmp_path)
    job.finish()
    pf = str(tmp_path / "r.port")
    proc = subprocess.Popen(
        [sys.executable, "-m", "tpu_cooccurrence.serving.replica",
         "--state-dir", d, "--port", "0", "--port-file", pf,
         "--poll-interval-s", "0.2", "--run-seconds", "4"],
        cwd=REPO, stderr=subprocess.PIPE, text=True)
    try:
        info = _wait_port(pf)
        live = ckpt.generations(d, "")[0][0]
        deadline = time.monotonic() + 10
        gen = -1
        while time.monotonic() < deadline and gen < live:
            with urllib.request.urlopen(info["url"] + "/healthz",
                                        timeout=2) as resp:
                gen = json.load(resp)["replica"]["generation"]
            time.sleep(0.2)
        assert gen == live
        with urllib.request.urlopen(
                info["url"] + "/recommend?user=3&n=5",
                timeout=2) as resp:
            assert json.load(resp)["generation"] == live
        rc = proc.wait(timeout=30)
        assert rc == 0, proc.stderr.read()
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()


@pytest.mark.slow
def test_fleet_chaos_kill_one_replica_zero_errors_after_drain(tmp_path):
    """The acceptance chaos case: 2-replica fleet under the serving
    gang supervisor against a live ingest; SIGKILL one replica
    mid-storm. The drained client (the survivor) serves zero failed
    queries throughout, and the relaunched replica re-syncs from
    checkpoint + delta tail to the live generation — no writer
    involvement at any point."""
    import signal

    d, job, (users, items, ts), half = _spawn_writer_dir(tmp_path)
    fleet_dir = str(tmp_path / "fleet")
    proc = subprocess.Popen(
        [sys.executable, "-m", "tpu_cooccurrence.serving.replica",
         "--state-dir", d, "--fleet", "2", "--fleet-dir", fleet_dir,
         "--poll-interval-s", "0.2", "--run-seconds", "45",
         "--gang-stale-after-s", "0"],
        cwd=REPO, stderr=subprocess.PIPE, text=True)
    try:
        i0 = _wait_port(os.path.join(fleet_dir, "replica.p0.port"))
        i1 = _wait_port(os.path.join(fleet_dir, "replica.p1.port"))
        # Live ingest continues while the fleet serves.
        for lo in range(half, len(users), 97):
            job.add_batch(users[lo:lo + 97], items[lo:lo + 97],
                          ts[lo:lo + 97])
        job.finish()
        live = ckpt.generations(d, "")[0][0]
        os.kill(i0["pid"], signal.SIGKILL)
        # The drained client hammers the survivor: zero failures.
        errors = queries = 0
        relaunched = None
        t0 = time.monotonic()
        while time.monotonic() - t0 < 30:
            try:
                with urllib.request.urlopen(
                        i1["url"] + "/recommend?user=3&n=5",
                        timeout=2) as resp:
                    json.load(resp)
                queries += 1
            except Exception:
                errors += 1
            try:
                with open(os.path.join(fleet_dir,
                                       "replica.p0.port")) as f:
                    info = json.load(f)
                if info["pid"] != i0["pid"]:
                    with urllib.request.urlopen(
                            info["url"] + "/healthz",
                            timeout=2) as resp:
                        h = json.load(resp)
                    if h["replica"]["generation"] >= live:
                        relaunched = h
                        break
            except Exception:
                pass
            time.sleep(0.2)
        assert queries > 0 and errors == 0, (queries, errors)
        assert relaunched is not None, "slot 0 never re-synced"
        assert relaunched["replica"]["generation"] == live
        assert relaunched["replica"]["bootstrap_generation"] <= live
    finally:
        if proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=20)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()


@pytest.mark.slow
def test_multi_replica_storm_identical_tags(writer_repo, tmp_path):
    """Multi-replica storm (in-process): every replica converges to the
    same generation tag over the same log, and a client pool spread
    across them sees zero errors and one consistent tag."""
    reps = []
    srvs = []
    for _ in range(3):
        rep = ReadReplica(writer_repo["early"])
        rep.bootstrap()
        rep.state_dir = writer_repo["live"]
        rep.poll()
        reps.append(rep)
        srvs.append(ReplicaServer(REGISTRY, rep, port=0).start())
    try:
        live = ckpt.generations(writer_repo["live"], "")[0][0]
        assert all(r.generation == live for r in reps)
        errors = []
        tags = []

        def client(tid):
            rng = np.random.default_rng(tid)
            for _ in range(40):
                srv = srvs[int(rng.integers(0, len(srvs)))]
                try:
                    with urllib.request.urlopen(
                            f"http://127.0.0.1:{srv.port}/recommend"
                            f"?user={int(rng.integers(0, 30))}&n=5"
                            f"&min_gen={live}", timeout=5) as resp:
                        tags.append(json.load(resp)["generation"])
                except Exception as exc:  # noqa: BLE001 - tallied
                    errors.append(exc)

        pool = [threading.Thread(target=client, args=(t,))
                for t in range(4)]
        for t in pool:
            t.start()
        for t in pool:
            t.join(timeout=60)
        assert not errors, errors[:3]
        assert set(tags) == {live}
    finally:
        for s in srvs:
            s.stop()
