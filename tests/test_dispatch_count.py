"""Pin the sparse backend's per-window device-dispatch COUNT.

The round-2 performance claim ("a steady-state sparse window is two
device dispatches: one fused moves+update, one fused-window scoring" —
docs/PERFORMANCE.md) is behaviorally invisible on CPU: an accidental
extra dispatch or a plan-churn recompile would still produce correct
results, just 10x slower on a high-latency tunnel. These tests wrap the
module-level jitted callables with counters and assert the counts, so a
dispatch-count regression fails CI on CPU (VERDICT r2, Next #5).
"""

import numpy as np
import pytest

import jax

import tpu_cooccurrence.state.sparse_scorer as ss
from tpu_cooccurrence.sampling.reservoir import PairDeltaBatch


class DispatchCounter:
    """Counting shims around the sparse scorer's jitted entry points."""

    TRACKED = ("_apply_update", "_apply_moves_update",
               "_apply_update_chunked", "_apply_moves_update_chunked",
               "_apply_update_packed", "_apply_moves_update_packed",
               "_score_slab", "_score_into_table",
               "_score_window_into_table", "_grow", "_compact_gather",
               "_fused_sparse_window_packed", "_fused_sparse_window_raw")

    def __init__(self, monkeypatch):
        self.counts = {name: 0 for name in self.TRACKED}
        self.plans = []  # static plan of every fused-window dispatch
        for name in self.TRACKED:
            monkeypatch.setattr(ss, name, self._wrap(name, getattr(ss, name)))

    def _wrap(self, name, fn):
        def counted(*args, **kwargs):
            self.counts[name] += 1
            if name == "_score_window_into_table":
                self.plans.append(kwargs["plan"])
            return fn(*args, **kwargs)
        return counted

    def reset(self):
        for name in self.TRACKED:
            self.counts[name] = 0

    @property
    def updates(self):
        return (self.counts["_apply_update"]
                + self.counts["_apply_moves_update"]
                + self.counts["_apply_update_chunked"]
                + self.counts["_apply_moves_update_chunked"]
                + self.counts["_apply_update_packed"]
                + self.counts["_apply_moves_update_packed"])

    @property
    def fused(self):
        return (self.counts["_fused_sparse_window_packed"]
                + self.counts["_fused_sparse_window_raw"])

    @property
    def window_scores(self):
        return self.counts["_score_window_into_table"]

    @property
    def bucket_scores(self):
        return self.counts["_score_slab"] + self.counts["_score_into_table"]


def _window(rng, n_pairs, vocab):
    src = rng.integers(0, vocab, n_pairs)
    dst = rng.integers(0, vocab, n_pairs)
    move = dst == src
    dst[move] = (dst[move] + 1) % vocab
    return PairDeltaBatch(src.astype(np.int64), dst.astype(np.int64),
                          np.ones(n_pairs, dtype=np.int32))


def test_fixed_shape_window_is_two_dispatches(monkeypatch):
    """Steady state, fixed shapes: 1 update (+moves fused) + 1 scoring."""
    counter = DispatchCounter(monkeypatch)
    # Capacity sized so the slab/heap never outgrows it over the whole
    # stream (<= 20k distinct cells): steady state means NO growth or
    # compaction dispatches, only the two hot ones.
    scorer = ss.SparseDeviceScorer(
        top_k=5, defer_results=True, fixed_shapes=True,
        capacity=1 << 18, items_capacity=1 << 10)
    rng = np.random.default_rng(42)
    vocab = 300

    # Warmup: capacity growth, first compactions, and plan discovery are
    # allowed to cost extra dispatches while shapes are still being seen.
    for w in range(5):
        scorer.process_window(w * 10, _window(rng, 800, vocab))

    for w in range(5, 25):
        counter.reset()
        scorer.process_window(w * 10, _window(rng, 800, vocab))
        assert counter.updates == 1, (
            f"window {w}: {counter.updates} update dispatches "
            f"(moves must ride the update)")
        assert counter.window_scores == 1, (
            f"window {w}: {counter.window_scores} fused-window score "
            f"dispatches (expected exactly 1)")
        assert counter.bucket_scores == 0, (
            f"window {w}: per-bucket score dispatch leaked into "
            f"fixed-shape mode")
        assert counter.counts["_grow"] == 0, (
            f"window {w}: slab regrew in steady state")
        assert counter.counts["_compact_gather"] == 0, (
            f"window {w}: compaction ran in steady state")


def test_fixed_shape_plan_is_monotone_and_bounded(monkeypatch):
    """The fused program's static plan only grows; compile count (== number
    of distinct plans XLA sees) is bounded by the final plan's rectangle
    count — at most one program per (bucket, chunk-rank) ever occupied."""
    counter = DispatchCounter(monkeypatch)
    scorer = ss.SparseDeviceScorer(
        top_k=5, defer_results=True, fixed_shapes=True,
        capacity=1 << 15, items_capacity=1 << 10)
    rng = np.random.default_rng(7)

    # Vary the window size and vocab reach so buckets appear over time.
    for w, (n, vocab) in enumerate(
            [(100, 40), (100, 40), (2000, 300), (400, 300), (4000, 600),
             (50, 600), (4000, 600), (800, 600), (3000, 600), (100, 40)]):
        scorer.process_window(w * 10, _window(rng, n, vocab))

    assert counter.plans, "fixed-shape mode never used the fused dispatch"
    # Monotone: each plan change strictly adds rectangles, never churns.
    prev = None
    distinct = []
    for plan in counter.plans:
        if plan != prev:
            if prev is not None and plan != prev:
                assert len(plan) > len(prev) or plan == prev, (
                    f"plan churned without growing: {prev} -> {plan}")
            distinct.append(plan)
            prev = plan
    final = counter.plans[-1]
    assert len(distinct) <= len(final), (
        f"{len(distinct)} distinct plans (compiles) for a final plan of "
        f"{len(final)} rectangles — plan churn means recompiles")
    # Every distinct plan is a prefix-extension of the previous: same
    # rectangles in canonical R order, new ones appended/merged in order.
    for a, b in zip(distinct, distinct[1:]):
        assert len(b) > len(a)


def test_variable_mode_defer_still_one_update(monkeypatch):
    """Variable (non-fixed) deferred mode: still exactly one update dispatch
    per window; scoring is one fused dispatch per occupied (bucket, chunk)."""
    counter = DispatchCounter(monkeypatch)
    scorer = ss.SparseDeviceScorer(
        top_k=5, defer_results=True, fixed_shapes=False,
        capacity=1 << 15, items_capacity=1 << 10)
    rng = np.random.default_rng(3)
    for w in range(5):
        scorer.process_window(w * 10, _window(rng, 800, 300))
    for w in range(5, 15):
        counter.reset()
        scorer.process_window(w * 10, _window(rng, 800, 300))
        assert counter.updates == 1
        assert counter.window_scores == 0
        assert counter.counts["_score_slab"] == 0  # defer: no downlink
        assert counter.counts["_score_into_table"] >= 1


def _clique_window(n_items: int = 40):
    """All ordered pairs of an n-item clique: the first window allocates
    every cell, every later identical window touches ONLY existing cells
    — the zero-relocation steady state the fused path owns."""
    items = np.arange(n_items)
    src, dst = np.meshgrid(items, items)
    sel = src != dst
    return PairDeltaBatch(src[sel].ravel().astype(np.int64),
                          dst[sel].ravel().astype(np.int64),
                          np.ones(int(sel.sum()), dtype=np.int32))


@pytest.mark.parametrize("wire", ["packed", "raw"])
def test_fused_sparse_steady_state_is_one_dispatch(monkeypatch, wire):
    """--fused-window on, sparse backend: a steady-state window (no
    relocation, no promotion, no growth) is exactly ONE device dispatch
    — the fused program; no separate update or score dispatch leaks."""
    from tpu_cooccurrence.observability.registry import REGISTRY

    counter = DispatchCounter(monkeypatch)
    scorer = ss.SparseDeviceScorer(
        top_k=5, defer_results=True, fused_window="on", wire_format=wire,
        cell_dtype="int16" if wire == "packed" else "int32",
        capacity=1 << 16, items_capacity=1 << 10)
    pairs = _clique_window()
    fused_gauge = REGISTRY.gauge("cooc_fused_dispatches_total")
    chained_gauge = REGISTRY.gauge("cooc_chained_dispatches_total")
    for w in range(3):  # warmup: allocation, growth, first compiles
        scorer.process_window(w * 10, pairs)
    f0, c0 = fused_gauge.get(), chained_gauge.get()
    for w in range(3, 10):
        counter.reset()
        scorer.process_window(w * 10, pairs)
        assert counter.fused == 1, (
            f"window {w}: {counter.fused} fused dispatches "
            f"({counter.counts})")
        assert counter.updates == 0, (
            f"window {w}: update dispatch leaked out of the fused "
            f"program ({counter.counts})")
        assert counter.window_scores == 0 and counter.bucket_scores == 0, (
            f"window {w}: score dispatch leaked out of the fused "
            f"program ({counter.counts})")
        assert counter.counts["_grow"] == 0
        assert counter.counts["_compact_gather"] == 0
    # The routing gauges split accordingly: 7 fused, 0 chained.
    assert fused_gauge.get() - f0 == 7
    assert chained_gauge.get() - c0 == 0
    # Shape specialization is bounded: the identical windows compiled
    # exactly one fused program shape.
    assert REGISTRY.gauge("cooc_fused_bucket_compilations_total").get() >= 1


def test_fused_sparse_relocation_window_falls_back_chained(monkeypatch):
    """A window that relocates rows (new cells outgrow pow2 caps) routes
    chained — plan.mv rides the chained moves+update dispatch — and the
    very next steady window is fused again; the gauges split per
    window."""
    from tpu_cooccurrence.observability.registry import REGISTRY

    counter = DispatchCounter(monkeypatch)
    scorer = ss.SparseDeviceScorer(
        top_k=5, defer_results=True, fused_window="on",
        wire_format="packed", capacity=1 << 16, items_capacity=1 << 10)
    pairs = _clique_window(24)
    for w in range(3):
        scorer.process_window(w * 10, pairs)
    fused_gauge = REGISTRY.gauge("cooc_fused_dispatches_total")
    chained_gauge = REGISTRY.gauge("cooc_chained_dispatches_total")
    f0, c0 = fused_gauge.get(), chained_gauge.get()
    # Every row gains 40 new partners: caps (pow2 of 23) outgrow, rows
    # relocate, the window MUST route chained.
    counter.reset()
    grow = _clique_window(64)
    scorer.process_window(100, grow)
    assert counter.fused == 0, counter.counts
    assert counter.updates == 1, counter.counts
    assert scorer.last_dispatch_fused is False
    assert chained_gauge.get() - c0 == 1
    # Steady again: the relocated layout syncs through the registry
    # delta and the next window is back to one fused dispatch.
    counter.reset()
    scorer.process_window(110, grow)
    assert counter.fused == 1, counter.counts
    assert counter.updates == 0, counter.counts
    assert scorer.last_dispatch_fused is True
    assert fused_gauge.get() - f0 == 1


class ShardedDispatchCounter:
    """Counting shims around a ShardedSparseScorer's per-instance jitted
    callables. The sharded programs are instance-level closures (the
    mesh is baked in), so the module-level monkeypatch idiom above
    cannot see them — instead every cached-program *getter* is wrapped
    so the callable it returns counts its invocations, plus the direct
    ``_update`` attribute. Attach AFTER warmup: ``_build_update()``
    replaces ``_update`` on growth, which would silently unwrap it."""

    GETTERS = ("_moves_fn", "_score_fn", "_score_window_into_fn",
               "_grow_fn", "_compact_gather_fn", "_promote_fn",
               "_fused_fn")

    def __init__(self, scorer):
        self.scorer = scorer
        self.counts = {name: 0 for name in self.GETTERS + ("_update",)}
        for name in self.GETTERS:
            setattr(scorer, name,
                    self._wrap_getter(name, getattr(scorer, name)))
        orig_update = scorer._update

        def counted_update(*args, **kwargs):
            self.counts["_update"] += 1
            return orig_update(*args, **kwargs)

        scorer._update = counted_update

    def _wrap_getter(self, name, getter):
        def counting_getter(*args, **kwargs):
            fn = getter(*args, **kwargs)

            def counted(*fargs, **fkwargs):
                self.counts[name] += 1
                return fn(*fargs, **fkwargs)

            return counted

        return counting_getter

    def reset(self):
        for name in self.counts:
            self.counts[name] = 0

    @property
    def total(self):
        return sum(self.counts.values())


@pytest.mark.skipif(len(jax.devices()) < 2, reason="needs 2 devices")
@pytest.mark.parametrize("wire", ["packed", "raw"])
def test_fused_sharded_steady_state_is_one_launch_per_worker(wire):
    """--fused-window on, sharded sparse: a steady-state window is
    exactly ONE jit(shard_map) launch — decode + update + psum + mirror
    sync + rescore + table scatter; no chained update or score program
    leaks beside it, on the packed and the raw wire alike."""
    from tpu_cooccurrence.parallel.sharded_sparse import ShardedSparseScorer

    scorer = ShardedSparseScorer(
        5, num_shards=2, defer_results=True, fused_window="on",
        wire_format=wire,
        cell_dtype="int16" if wire == "packed" else "int32")
    pairs = _clique_window()
    for w in range(3):  # warmup: allocation, cold plan-rebuild, compile
        scorer.process_window(w * 10, pairs)
    assert scorer.last_dispatch_fused is True, "warmup never fused"
    counter = ShardedDispatchCounter(scorer)
    for w in range(3, 8):
        counter.reset()
        scorer.process_window(w * 10, pairs)
        assert counter.counts["_fused_fn"] == 1, (
            f"window {w}: {counter.counts}")
        assert counter.total == 1, (
            f"window {w}: a dispatch leaked beside the fused launch "
            f"({counter.counts})")
        assert scorer.last_dispatch_fused is True
    # The identical windows compiled exactly one fused program shape.
    assert scorer.fused_compilations == 1


@pytest.mark.skipif(len(jax.devices()) < 2, reason="needs 2 devices")
def test_fused_sharded_relocation_falls_back_then_one_launch():
    """A relocation window (rows outgrow pow2 caps) routes chained on
    the sharded path — no fused launch — and the next repeat of the
    same population is back to exactly one launch."""
    from tpu_cooccurrence.parallel.sharded_sparse import ShardedSparseScorer

    scorer = ShardedSparseScorer(
        5, num_shards=2, defer_results=True, fused_window="on")
    pairs = _clique_window(24)
    for w in range(3):
        scorer.process_window(w * 10, pairs)
    assert scorer.last_dispatch_fused is True
    counter = ShardedDispatchCounter(scorer)
    grow = _clique_window(64)
    scorer.process_window(100, grow)
    assert counter.counts["_fused_fn"] == 0, counter.counts
    assert scorer.last_dispatch_fused is False
    assert scorer.last_fallback_reason == "relocation"
    # Re-attach: the growth window may have rebuilt ``_update``.
    counter = ShardedDispatchCounter(scorer)
    scorer.process_window(110, grow)
    assert counter.counts["_fused_fn"] == 1, counter.counts
    assert counter.total == 1, counter.counts
    assert scorer.last_dispatch_fused is True


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
def test_sharded_sparse_program_cache_is_monotone():
    """The sharded-sparse fused-window program cache grows monotonically and
    stays bounded by the plan count (no per-window recompiles)."""
    from tpu_cooccurrence.parallel.mesh import make_mesh
    from tpu_cooccurrence.parallel.sharded_sparse import ShardedSparseScorer

    mesh = make_mesh(8, devices=jax.devices()[:8])
    scorer = ShardedSparseScorer(5, mesh=mesh, defer_results=True,
                                 fixed_shapes=True)
    rng = np.random.default_rng(11)
    sizes = []
    for w in range(12):
        scorer.process_window(w * 10, _window(rng, 600, 200))
        sizes.append(len(scorer._score_window_fns))
    assert sizes == sorted(sizes), "program cache shrank (cache churn)"
    # Steady state: the last windows add no new programs.
    assert sizes[-1] == sizes[-4], (
        f"program cache still growing at window 12: {sizes}")
