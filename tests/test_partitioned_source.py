"""PartitionedLogSource (ISSUE 18): the Kafka shape on plain files.

The contracts under test:

* **Deterministic interleave** — chunked round-robin over the
  lexicographic ``part-*`` order, identical across independent readers
  (the replicated-ingest invariant the sharded backends assume).
* **Exactly-once resume** — at EVERY consumption position, a source
  restored from ``offsets_state()`` delivers precisely the unconsumed
  suffix: no byte re-read, no record dropped.
* **Poison partitions lag alone** — a rewritten/shrunk partition is
  quarantined (dead-letter record + journaled event) while healthy
  partitions keep flowing; the quarantine flag rides the next
  committed section.
* **Record framing** — a torn tail (no newline yet) is deferred in
  continuous mode so a committed offset never splits a record.
"""

import json
import os

import pytest

from tpu_cooccurrence.io.partitioned import PartitionedLogSource


def write_partitions(root, counts=(40, 40, 40)):
    root.mkdir()
    for p, n in enumerate(counts):
        (root / f"part-{p:03d}").write_text(
            "".join(f"p{p}:{i}\n" for i in range(n)))
    return str(root)


def drain(source):
    return [line for line in source.lines() if line is not None]


class RecordingQuarantine:
    def __init__(self):
        self.records = []

    def quarantine(self, path, lineno, raw, reason):
        self.records.append((path, lineno, raw, reason))


def pump(it, limit=50):
    """Next non-heartbeat record from a continuous source (or None if
    ``limit`` heartbeats pass without one)."""
    for _ in range(limit):
        value = next(it)
        if value is not None:
            return value
    return None


# -- deterministic interleave ------------------------------------------


def test_interleave_is_deterministic_and_chunked(tmp_path):
    root = write_partitions(tmp_path / "plog", counts=(5, 9, 2))
    a = drain(PartitionedLogSource(root, turn_records=3))
    b = drain(PartitionedLogSource(root, turn_records=3))
    assert a == b
    # First full rotation: 3 records per partition, part order fixed by
    # the lexicographic sort.
    assert a[:8] == ["p0:0", "p0:1", "p0:2",
                     "p1:0", "p1:1", "p1:2",
                     "p2:0", "p2:1"]
    # Every record exactly once.
    expected = [f"p{p}:{i}" for p, n in enumerate((5, 9, 2))
                for i in range(n)]
    assert sorted(a) == sorted(expected)


def test_single_file_degenerate(tmp_path):
    f = tmp_path / "events.csv"
    f.write_text("a\nb\nc\n")
    src = PartitionedLogSource(str(f))
    assert drain(src) == ["a", "b", "c"]
    section = src.offsets_state()
    assert list(section["partitions"]) == ["events.csv"]
    assert section["partitions"]["events.csv"]["records"] == 3


def test_expected_partitions_mismatch_raises(tmp_path):
    root = write_partitions(tmp_path / "plog")
    with pytest.raises(ValueError, match="offset contract"):
        next(PartitionedLogSource(root, expected_partitions=4).lines())
    # The matching count is accepted.
    assert drain(PartitionedLogSource(root, expected_partitions=3))


# -- exactly-once resume -----------------------------------------------


def test_resume_at_every_position_is_exactly_once(tmp_path):
    """The exhaustive sweep: checkpoint after k records for every k,
    restore a fresh source from the section, and the suffix completes
    the full stream with no overlap and no gap — including mid-turn
    cursors and partition-exhaustion boundaries."""
    root = write_partitions(tmp_path / "plog", counts=(5, 8, 3))
    full = drain(PartitionedLogSource(root, turn_records=3))
    assert len(full) == 16
    for k in range(len(full) + 1):
        src = PartitionedLogSource(root, turn_records=3)
        it = src.lines()
        got = [next(it) for _ in range(k)]
        assert got == full[:k], k
        # The JSON round-trip mirrors the npz meta the section rides.
        section = json.loads(json.dumps(src.offsets_state()))
        resumed = PartitionedLogSource(root, turn_records=3)
        resumed.restore_offsets(section)
        assert got + drain(resumed) == full, k


def test_offsets_advance_before_yield(tmp_path):
    """A checkpoint taken at any batch boundary covers every delivered
    record: the committed record count equals the yield count."""
    root = write_partitions(tmp_path / "plog", counts=(4, 4, 4))
    src = PartitionedLogSource(root, turn_records=3)
    it = src.lines()
    for k in range(1, 9):
        next(it)
        section = src.offsets_state()
        committed = sum(e["records"]
                        for e in section["partitions"].values())
        assert committed == k


# -- poison partitions --------------------------------------------------


def test_rewritten_partition_quarantined_on_restore(tmp_path):
    root = write_partitions(tmp_path / "plog", counts=(6, 6, 6))
    src = PartitionedLogSource(root, turn_records=3)
    it = src.lines()
    got = [next(it) for _ in range(6)]  # 3 from p0, 3 from p1
    section = src.offsets_state()
    assert section["partitions"]["part-001"]["byte_offset"] > 0
    # Rewrite part-001 in place: same size, different bytes — the
    # committed head-prefix hash no longer matches.
    p1 = os.path.join(root, "part-001")
    size = os.path.getsize(p1)
    with open(p1, "wb") as f:
        f.write(b"X" * (size - 1) + b"\n")

    resumed = PartitionedLogSource(root, turn_records=3)
    events = []
    q = RecordingQuarantine()
    resumed.attach(quarantine=q, on_event=events.append)
    resumed.restore_offsets(section)
    rest = drain(resumed)
    # Healthy partitions keep flowing; the poisoned one lags alone —
    # none of its bytes (old or rewritten) reach the stream again.
    assert all(not r.startswith("X") and not r.startswith("p1")
               for r in rest)
    assert sorted(rest) == sorted(
        [f"p0:{i}" for i in range(3, 6)] + [f"p2:{i}" for i in range(6)])
    assert events == ["ingest/partition-quarantined:part-001"]
    assert q.records and "rewritten under a checkpoint" in q.records[0][3]
    # The quarantine flag rides the next committed section.
    next_section = resumed.offsets_state()
    assert next_section["partitions"]["part-001"]["quarantined"] is True


def test_quarantined_flag_round_trips(tmp_path):
    """A partition quarantined before a checkpoint stays quarantined
    after restore — no verification re-run resurrects it."""
    root = write_partitions(tmp_path / "plog", counts=(3, 3))
    src = PartitionedLogSource(root, turn_records=2)
    consume_all = drain(src)
    assert consume_all
    section = src.offsets_state()
    section["partitions"]["part-000"]["quarantined"] = True
    resumed = PartitionedLogSource(root, turn_records=2)
    resumed.restore_offsets(json.loads(json.dumps(section)))
    drain(resumed)
    assert resumed.offsets_state()["partitions"]["part-000"][
        "quarantined"] is True


def test_shrunk_partition_quarantined_mid_run(tmp_path):
    """Continuous-mode poll guard: a partition whose file shrank below
    the committed offset is quarantined mid-run; appends to healthy
    partitions keep flowing."""
    root = write_partitions(tmp_path / "plog", counts=(3, 3))
    src = PartitionedLogSource(root, process_continuously=True,
                               poll_interval_s=0.0, turn_records=2)
    events = []
    q = RecordingQuarantine()
    src.attach(quarantine=q, on_event=events.append)
    it = src.lines()
    got = [pump(it) for _ in range(6)]
    assert sorted(got) == sorted(
        [f"p0:{i}" for i in range(3)] + [f"p1:{i}" for i in range(3)])
    # Truncate part-000 below its committed offset.
    with open(os.path.join(root, "part-000"), "wb") as f:
        f.write(b"p0:0\n")
    # Append to the healthy partition: it must still be delivered.
    with open(os.path.join(root, "part-001"), "ab") as f:
        f.write(b"p1:new\n")
    assert pump(it) == "p1:new"
    # Drain to an idle round so the poll-time append-only check runs.
    assert pump(it, limit=4) is None
    assert "ingest/partition-quarantined:part-000" in events
    assert any("shrank below the committed offset" in r[3]
               for r in q.records)


def test_missing_and_unknown_partitions_warn(tmp_path, caplog):
    import logging

    root = write_partitions(tmp_path / "plog", counts=(3, 3))
    src = PartitionedLogSource(root, turn_records=2)
    section = drain(src) and src.offsets_state()
    # A checkpointed partition that vanished + a live one that was
    # never checkpointed both warn (and neither aborts the restore).
    section["partitions"]["part-999"] = section["partitions"].pop(
        "part-001")
    resumed = PartitionedLogSource(root, turn_records=2)
    resumed.restore_offsets(section)
    with caplog.at_level(logging.WARNING,
                         logger="tpu_cooccurrence.io.partitioned"):
        rest = drain(resumed)
    assert "is gone" in caplog.text
    assert "reading it from the start" in caplog.text
    # The un-checkpointed partition really was re-read from the start.
    assert rest == [f"p1:{i}" for i in range(3)]


# -- record framing ----------------------------------------------------


def test_torn_tail_is_deferred_until_complete(tmp_path):
    root = tmp_path / "plog"
    root.mkdir()
    (root / "part-000").write_text("a\nb\nc")  # torn tail: no newline
    src = PartitionedLogSource(str(root), process_continuously=True,
                               poll_interval_s=0.0, turn_records=4)
    it = src.lines()
    assert pump(it) == "a"
    assert pump(it) == "b"
    assert pump(it, limit=5) is None  # "c" is torn — deferred
    offsets = src.offsets_state()["partitions"]["part-000"]
    assert offsets["records"] == 2  # the committed offset excludes it
    with open(root / "part-000", "ab") as f:
        f.write(b"\n")
    assert pump(it) == "c"


def test_process_once_reads_torn_tail(tmp_path):
    """PROCESS_ONCE has no writer to wait for: the snapshot is final,
    so a missing trailing newline still yields the last record."""
    root = tmp_path / "plog"
    root.mkdir()
    (root / "part-000").write_text("a\nb\nc")
    assert drain(PartitionedLogSource(str(root))) == ["a", "b", "c"]


# -- health / ownership ------------------------------------------------


def test_ingest_health_shape_and_ownership(tmp_path):
    root = write_partitions(tmp_path / "plog", counts=(4, 4, 4))
    src = PartitionedLogSource(root, turn_records=3, process_id=0,
                               num_processes=2)
    assert src.ingest_health() is None  # pre-discovery: nothing to say
    it = src.lines()
    for _ in range(5):
        next(it)
    health = src.ingest_health()
    assert health["format"] == "partitioned"
    assert health["quarantined_partitions"] == 0
    assert set(health["partitions"]) == {"part-000", "part-001",
                                         "part-002"}
    entry = health["partitions"]["part-000"]
    assert set(entry) == {"byte_offset", "records", "lag",
                          "quarantined", "owner"}
    # Modular ownership at the current topology.
    assert [health["partitions"][n]["owner"]
            for n in sorted(health["partitions"])] == [0, 1, 0]
    # Lag is live bytes-behind: file size minus committed offset.
    size = os.path.getsize(os.path.join(root, "part-000"))
    assert entry["lag"] == size - entry["byte_offset"]
    assert src.partition_owner(5) == 5 % 2
