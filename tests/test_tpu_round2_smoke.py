"""CPU smoke tests for the on-chip measurement machinery.

The tpu_round2 passes only ever execute on a scarce TPU grant; an
import error, renamed helper, or signature drift inside one would
otherwise surface for the first time MID-GRANT and burn the session
(the 2026-07-31 capture lost config4 to exactly this failure class,
though that one was a transient backend error). These tests run the
cheap machinery end to end on CPU — subprocess, exit codes, JSONL rows,
env pinning — without the heavy measurement bodies.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ENV = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")


def _read_jsonl(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def test_tunnel_probe_stage_end_to_end(tmp_path):
    """The cheapest real pass runs as grant_watch would run it: own
    subprocess, --only selection, exit 0, rows appended to the
    overridden artifact (env + measurement), never the tracked file."""
    out = tmp_path / "rounds.jsonl"
    r = subprocess.run(
        [sys.executable, "-m", "tpu_cooccurrence.bench.tpu_round2",
         "--quick", "--only", "tunnel-probe"],
        env=dict(ENV, TPU_ROUND2_OUT=str(out)),
        capture_output=True, text=True, timeout=300, cwd=REPO)
    assert r.returncode == 0, r.stderr[-1500:]
    rows = _read_jsonl(out)
    names = [x["name"] for x in rows]
    assert names == ["env", "tunnel-probe"]
    probe = rows[1]
    assert probe["ok"] is True
    # ADVICE r4: every measurement row carries its own platform tag so a
    # row from a session whose tunnel-probe stage was skipped can still
    # be told apart from an accidental CPU run. Distinct key from the
    # job-backend "backend" field some measurements also record.
    assert probe["jax_platform"] == "cpu"
    for key in ("sync_ms_per_dispatch", "enqueue_ms_per_dispatch",
                "upload_256kb_ms", "upload_1024kb_ms",
                "upload_4x256kb_ms", "fetch_320kb_ms"):
        assert key in probe, key


def test_env_row_only_with_tunnel_probe(tmp_path):
    """Per-measurement stages must not spam one env row each into the
    artifact: only the tunnel-probe stage (or a full run) writes it."""
    out = tmp_path / "rounds.jsonl"
    r = subprocess.run(
        [sys.executable, "-m", "tpu_cooccurrence.bench.tpu_round2",
         "--quick", "--only", "config4-headline"],
        env=dict(ENV, TPU_ROUND2_OUT=str(out),
                 TPU_COOC_SMOKE_EVENTS="2000"),
        capture_output=True, text=True, timeout=600, cwd=REPO)
    assert r.returncode == 0, r.stderr[-1500:]
    names = [x["name"] for x in _read_jsonl(out)]
    assert names == ["config4-headline"]


def test_smoke_events_ignored_off_cpu(monkeypatch):
    """A stale TPU_COOC_SMOKE_EVENTS export must not shrink a grant
    capture: the knob only applies on the cpu backend."""
    import jax

    from tpu_cooccurrence.bench import tpu_round2

    monkeypatch.setenv("TPU_COOC_SMOKE_EVENTS", "2000")
    assert tpu_round2._config4_events(quick=False) == 2_000  # cpu: honored
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    assert tpu_round2._config4_events(quick=False) == 1_000_000
    assert tpu_round2._config4_events(quick=True) == 200_000


def test_grant_watch_strips_smoke_env(monkeypatch, tmp_path):
    """grant_watch stages never inherit the smoke/redirect knobs —
    capture purity is owned by the watcher."""
    from tpu_cooccurrence.bench import grant_watch

    monkeypatch.setenv("TPU_COOC_SMOKE_EVENTS", "2000")
    monkeypatch.setenv("TPU_ROUND2_OUT", "/tmp/nope.jsonl")
    probe = tmp_path / "env.json"
    cmd = [sys.executable, "-c",
           "import json, os, sys; json.dump("
           "{k: os.environ.get(k) for k in ('TPU_COOC_SMOKE_EVENTS',"
           " 'TPU_ROUND2_OUT', 'PATH')}, open(sys.argv[1], 'w'))",
           str(probe)]
    status, _err = grant_watch.run_stage(
        "envprobe", cmd, 60.0, str(tmp_path / "w.jsonl"))
    assert status == "ok"
    env = json.loads(probe.read_text())
    assert env["TPU_COOC_SMOKE_EVENTS"] is None
    assert env["TPU_ROUND2_OUT"] is None
    assert env["PATH"], "the rest of the environment must pass through"


def test_stage_priority_and_load_provenance(tmp_path):
    """Capture stages run niced-up (grant time beats background work)
    in their own session, and stage-start records the 1-min loadavg so
    contended measurements are interpretable."""
    from tpu_cooccurrence.bench import grant_watch

    out = tmp_path / "nice.txt"
    # The parent renices right after spawn; sleep past that moment
    # before reading so the test does not race it.
    cmd = [sys.executable, "-c",
           "import os, sys, time; time.sleep(1.0); "
           "open(sys.argv[1], 'w').write("
           "f'{os.nice(0)} {os.getpgrp() == os.getpid()}')",
           str(out)]
    log = tmp_path / "w.jsonl"
    status, _err = grant_watch.run_stage("nice-probe", cmd, 60.0, str(log))
    assert status == "ok"
    niceness, own_group = out.read_text().split()
    assert own_group == "True", "stage must lead its own process group"
    # Root uid alone does not imply renice permission (CAP_SYS_NICE);
    # gate the assertion on an actual capability probe.
    try:
        os.setpriority(os.PRIO_PROCESS, 0,
                       os.getpriority(os.PRIO_PROCESS, 0) - 1)
        can_renice = True
        os.setpriority(os.PRIO_PROCESS, 0,
                       os.getpriority(os.PRIO_PROCESS, 0) + 1)
    except OSError:
        can_renice = False
    if can_renice:
        assert int(niceness) <= -5
    starts = [e for e in _read_jsonl(log) if e["event"] == "stage-start"]
    assert "load1" in starts[0]


def test_config4_passes_pin_their_env(tmp_path, monkeypatch):
    """config4-headline/-chunked must pin every A/B knob (ladder, fixed
    shapes, BOTH chunk knobs) against ambient operator settings, and
    restore them afterwards — contaminated arms decide hardware
    defaults on garbage."""
    from tpu_cooccurrence.bench import tpu_round2
    from tpu_cooccurrence.bench import configs

    monkeypatch.setattr(tpu_round2, "OUT", str(tmp_path / "o.jsonl"))
    monkeypatch.setenv("TPU_COOC_UPLOAD_CHUNKS", "4")       # ambient
    monkeypatch.setenv("TPU_COOC_UPLOAD_CHUNK_KB", "256")   # ambient
    monkeypatch.setenv("TPU_COOC_SCORE_LADDER", "64")       # ambient
    seen = []

    class FakeResult:
        pairs_per_sec = 123_456.0

        def as_dict(self):
            return {"name": "zipfian-1M-items", "pairs_per_sec": 123456.0,
                    "events": 1, "backend": "sparse"}

    def fake_config4(n_events):
        seen.append({k: os.environ.get(k) for k in
                     ("TPU_COOC_SCORE_LADDER", "TPU_COOC_FIXED_SCORE",
                      "TPU_COOC_UPLOAD_CHUNKS",
                      "TPU_COOC_UPLOAD_CHUNK_KB")})
        return FakeResult()

    monkeypatch.setattr(configs, "config4_zipfian_1m", fake_config4)
    assert tpu_round2.config4_headline(True) is True   # guard returns ok
    assert tpu_round2.config4_chunked(True) is True
    # Two runs (warmup + measure) per pass.
    assert len(seen) == 4
    for env in seen[:2]:   # headline: the monolithic arm
        assert env["TPU_COOC_UPLOAD_CHUNKS"] == "1"
        assert env["TPU_COOC_UPLOAD_CHUNK_KB"] == "0"
        assert env["TPU_COOC_SCORE_LADDER"] == "16"
        assert env["TPU_COOC_FIXED_SCORE"] == "1"
    for env in seen[2:]:   # chunked arm
        assert env["TPU_COOC_UPLOAD_CHUNKS"] == "4"
        assert env["TPU_COOC_SCORE_LADDER"] == "16"
    # Operator settings restored.
    assert os.environ["TPU_COOC_UPLOAD_CHUNKS"] == "4"
    assert os.environ["TPU_COOC_UPLOAD_CHUNK_KB"] == "256"
    assert os.environ["TPU_COOC_SCORE_LADDER"] == "64"
    rows = _read_jsonl(tmp_path / "o.jsonl")
    assert [r["name"] for r in rows] == ["config4-headline",
                                        "config4-chunked"]
    assert all(r["ok"] for r in rows)
    # The measurement name owns the row; the inner BenchResult's name
    # lands under "config".
    assert rows[0]["config"] == "zipfian-1M-items"
    # The JOB backend field (summarize.py keys on it) must survive the
    # platform tag — distinct keys, neither shadowing the other.
    import jax

    jax.devices()  # platform tag reads the cached backend
    assert rows[0]["backend"] == "sparse"
    assert tpu_round2._backend_tag() == {"jax_platform": "cpu"}


def test_bench_child_stderr_noise_filtered(tmp_path, monkeypatch, capsys):
    """The known-benign XLA machine-feature warning (+prefer-no-gather —
    it flooded the captured bench tails in BENCH_r0*.json) is withheld
    from the live stderr stream and surfaces as a count+sample debug
    field on the measurement JSON line; real warnings still stream."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_under_test", os.path.join(REPO, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    assert bench._is_benign_stderr(
        "AOT result. Target machine feature +prefer-no-gather is not "
        "supported on the host machine.")
    assert not bench._is_benign_stderr("XlaRuntimeError: RESOURCE_EXHAUSTED")

    fake = tmp_path / "fake_child.sh"
    fake.write_text(
        "#!/bin/sh\n"
        'echo \'{"value": 1.0, "unit": "pairs/s"}\'\n'
        'echo "Target machine feature +prefer-no-gather is not supported'
        ' on the host machine." >&2\n'
        'echo "a real warning that must stream through" >&2\n')
    fake.chmod(0o755)
    monkeypatch.setattr(sys, "executable", str(fake))
    line = bench._run_child(dict(os.environ), 60.0)
    assert line is not None
    rec = json.loads(line)
    assert rec["value"] == 1.0
    assert rec["stderr_noise"]["suppressed_lines"] == 1
    assert "+prefer-no-gather" in rec["stderr_noise"]["sample"]
    err = capsys.readouterr().err
    assert "a real warning that must stream through" in err
    assert "+prefer-no-gather" not in err
