"""Randomized cross-backend equivalence sweep.

The per-feature tests pin behaviors at fixed seeds; this sweep samples
the config space (tumbling/sliding, cuts on/off and tiny, random top-k,
random streams) and checks every backend against the float64 oracle:
identical counters, identical updated-row sets, scores at float32
tolerance, and ids wherever a position's score is untied — skipping the
final top-K position, which can legitimately tie with the first
*excluded* item (invisible to an in-list tie check) and then resolve by
each backend's documented tie order.
"""

import numpy as np
import pytest

from tpu_cooccurrence.config import Backend, Config
from tpu_cooccurrence.job import CooccurrenceJob


def _run(cfg, users, items, ts):
    job = CooccurrenceJob(cfg)
    job.add_batch(users, items, ts)
    job.finish()
    return (dict(job.counters.as_dict()),
            {i: job.latest[i] for i in job.latest})


@pytest.mark.parametrize("trial", range(6))
def test_randomized_backend_equivalence(trial):
    rng = np.random.default_rng(0x5EED + trial)
    n = int(rng.integers(200, 2000))
    n_users = int(rng.integers(2, 40))
    n_items = int(rng.integers(4, 120))
    users = rng.integers(0, n_users, n).astype(np.int64)
    items = rng.integers(0, n_items, n).astype(np.int64)
    ts = np.cumsum(rng.integers(0, 4, n)).astype(np.int64)
    kw = dict(window_size=int(rng.integers(3, 60)),
              seed=int(rng.integers(0, 2**31)),
              item_cut=int(rng.integers(1, 12)),
              user_cut=int(rng.integers(1, 8)),
              top_k=int(rng.integers(1, 12)),
              skip_cuts=bool(rng.integers(0, 2)))
    slide = None
    if trial % 3 == 0:
        base = int(rng.integers(2, 10))
        kw["window_size"] = base * int(rng.integers(2, 5))
        slide = base

    ref_c, ref_r = _run(
        Config(backend=Backend.ORACLE, window_slide=slide,
               development_mode=True, **kw), users, items, ts)
    for backend in ("device", "sparse", "hybrid"):
        c, r = _run(
            Config(backend=Backend(backend), window_slide=slide,
                   num_items=n_items if backend == "device" else 0,
                   development_mode=True, **kw), users, items, ts)
        assert c == ref_c, f"{backend} counters"
        assert set(r) == set(ref_r), f"{backend} row set"
        for item in ref_r:
            rv = np.asarray([s for _, s in ref_r[item]])
            bv = np.asarray([s for _, s in r[item]])
            assert len(rv) == len(bv), (backend, item)
            np.testing.assert_allclose(bv, rv, rtol=2e-4, atol=2e-4,
                                       err_msg=f"{backend} item {item}")
            for k in range(len(rv) - 1):
                if np.isclose(rv, rv[k], rtol=1e-5, atol=1e-6).sum() == 1:
                    assert ref_r[item][k][0] == r[item][k][0], \
                        f"{backend} item {item} pos {k}"
