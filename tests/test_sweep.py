"""Randomized cross-backend equivalence sweep.

The per-feature tests pin behaviors at fixed seeds; this sweep samples
the config space (tumbling/sliding, cuts on/off and tiny, random top-k,
random streams) and checks every backend against the float64 oracle
through the shared harness: identical counters and updated-row sets,
plus ``assert_latest_close``'s score/id protocol (f32-tolerance scores;
exact ids only for rows whose in-list score gaps dwarf the tolerance,
final rank excluded — the unseen K+1'th score may near-tie it).
"""

import numpy as np
import pytest

from tpu_cooccurrence.config import Backend, Config

from test_pipeline import assert_latest_close, run_production

# Randomized sweep: minutes of wall-clock. Slow lane (deselected by
# default; TPU_COOC_FULL_SUITE=1 or -m selects it back in).
pytestmark = pytest.mark.slow


@pytest.mark.parametrize("trial", range(6))
def test_randomized_backend_equivalence(trial):
    rng = np.random.default_rng(0x5EED + trial)
    n = int(rng.integers(200, 2000))
    n_users = int(rng.integers(2, 40))
    n_items = int(rng.integers(4, 120))
    users = rng.integers(0, n_users, n).astype(np.int64)
    items = rng.integers(0, n_items, n).astype(np.int64)
    ts = np.cumsum(rng.integers(0, 4, n)).astype(np.int64)
    kw = dict(window_size=int(rng.integers(3, 60)),
              seed=int(rng.integers(0, 2**31)),
              item_cut=int(rng.integers(1, 12)),
              user_cut=int(rng.integers(1, 8)),
              top_k=int(rng.integers(1, 12)),
              skip_cuts=bool(rng.integers(0, 2)))
    slide = None
    if trial % 3 == 0:
        base = int(rng.integers(2, 10))
        kw["window_size"] = base * int(rng.integers(2, 5))
        slide = base

    oracle = run_production(
        Config(backend=Backend.ORACLE, window_slide=slide,
               development_mode=True, **kw), users, items, ts)
    ref_latest = {i: oracle.latest[i] for i in oracle.latest}
    for backend in ("device", "sparse"):
        job = run_production(
            Config(backend=Backend(backend), window_slide=slide,
                   num_items=n_items if backend == "device" else 0,
                   development_mode=True, **kw), users, items, ts)
        assert job.counters.as_dict() == oracle.counters.as_dict(), backend
        # Tighter-than-default score tolerance (the harness default atol
        # of 1e-3 is for adversarial row-sum magnitudes; these streams
        # stay small). The gap-gated id protocol is the safe one.
        assert_latest_close(ref_latest,
                            {i: job.latest[i] for i in job.latest},
                            rtol=2e-4, atol=2e-4)
