"""Hybrid backend (host sparse rows + device batched scoring) tests."""

import numpy as np

from tpu_cooccurrence.config import Backend, Config
from tpu_cooccurrence.metrics import (
    OBSERVED_COOCCURRENCES,
    RESCORED_ITEMS,
    ROW_SUM_PROCESS_WINDOW,
)

from test_pipeline import (
    assert_latest_close,
    random_stream,
    relabel_first_appearance,
    run_production,
)


def test_hybrid_matches_oracle_backend():
    for overrides in [dict(skip_cuts=True), dict(item_cut=5, user_cut=4)]:
        kw = dict(window_size=10, seed=0xBEEF, development_mode=True)
        kw.update(overrides)
        users, items, ts = random_stream(31)
        a = run_production(Config(**kw, backend=Backend.ORACLE), users, items, ts)
        b = run_production(Config(**kw, backend=Backend.HYBRID), users, items, ts)
        assert_latest_close(a.latest, b.latest)
        for name in (OBSERVED_COOCCURRENCES, ROW_SUM_PROCESS_WINDOW,
                     RESCORED_ITEMS):
            assert a.counters.get(name) == b.counters.get(name), name


def test_hybrid_needs_no_vocab_capacity():
    # The whole point: arbitrary item ids without --num-items.
    cfg = Config(window_size=10, seed=2, skip_cuts=True, backend=Backend.HYBRID)
    users, items, ts = random_stream(32, n_items=500)
    job = run_production(cfg, users, items, ts)
    assert job.latest


def test_hybrid_mixed_short_and_long_rows_across_windows():
    """Windows mixing host-scored short rows (<= HOST_ROW_MAX nonzeros) with
    device-scored long rows, spanning several process_window calls so host
    chunks flow through the one-window-deep pipeline and _materialize."""
    from tpu_cooccurrence.state.hybrid_scorer import HybridScorer

    assert HybridScorer.HOST_ROW_MAX == 32  # stream sized against this
    kw = dict(window_size=25, seed=0xD0, skip_cuts=True,
              development_mode=True)
    # Head items co-occur with ~60 partners (device path); tail items with
    # only a few (host path). Zipf-ish: item 0..4 hot, 5..119 cold.
    rng = np.random.default_rng(7)
    n = 2000
    users = rng.integers(0, 8, n)
    hot = rng.integers(0, 5, n)
    cold = rng.integers(5, 120, n)
    items = np.where(rng.random(n) < 0.4, hot, cold)
    ts = np.cumsum(rng.integers(0, 2, n)).astype(np.int64)
    users = relabel_first_appearance(users)
    items = relabel_first_appearance(items)

    a = run_production(Config(**kw, backend=Backend.ORACLE), users, items, ts)
    b = run_production(Config(**kw, backend=Backend.HYBRID), users, items, ts)
    # The stream must actually have exercised BOTH scoring paths, or this
    # test no longer covers the host-chunk branch of _materialize.
    assert b.scorer.dispatched_host_chunks > 0
    assert b.scorer.dispatched_device_chunks > 0
    assert_latest_close(a.latest, b.latest)


def test_hybrid_checkpoint_roundtrip(tmp_path):
    from tpu_cooccurrence.job import CooccurrenceJob

    kw = dict(window_size=10, seed=4, item_cut=5, user_cut=3,
              backend=Backend.HYBRID, checkpoint_dir=str(tmp_path / "ck"),
              development_mode=True)
    users, items, ts = random_stream(33, n=400)
    half = 180

    ref = CooccurrenceJob(Config(**kw))
    ref.add_batch(users, items, ts)
    ref.finish()

    a = CooccurrenceJob(Config(**kw))
    a.add_batch(users[:half], items[:half], ts[:half])
    a.checkpoint()
    b = CooccurrenceJob(Config(**kw))
    b.restore()
    b.add_batch(users[half:], items[half:], ts[half:])
    b.finish()

    assert set(ref.latest) == set(b.latest)
    for item in ref.latest:
        np.testing.assert_allclose(
            np.array([s for _, s in b.latest[item]]),
            np.array([s for _, s in ref.latest[item]]), rtol=1e-6, atol=1e-6)
