"""Hybrid backend (host sparse rows + device batched scoring) tests."""

import numpy as np

from tpu_cooccurrence.config import Backend, Config
from tpu_cooccurrence.metrics import (
    OBSERVED_COOCCURRENCES,
    RESCORED_ITEMS,
    ROW_SUM_PROCESS_WINDOW,
)

from test_pipeline import random_stream, run_production


def test_hybrid_matches_oracle_backend():
    for overrides in [dict(skip_cuts=True), dict(item_cut=5, user_cut=4)]:
        kw = dict(window_size=10, seed=0xBEEF, development_mode=True)
        kw.update(overrides)
        users, items, ts = random_stream(31)
        a = run_production(Config(**kw, backend=Backend.ORACLE), users, items, ts)
        b = run_production(Config(**kw, backend=Backend.HYBRID), users, items, ts)
        assert set(a.latest) == set(b.latest)
        for item in a.latest:
            o = np.array([s for _, s in a.latest[item]])
            h = np.array([s for _, s in b.latest[item]])
            assert len(o) == len(h)
            np.testing.assert_allclose(h, o, rtol=1e-4, atol=1e-3)
        for name in (OBSERVED_COOCCURRENCES, ROW_SUM_PROCESS_WINDOW,
                     RESCORED_ITEMS):
            assert a.counters.get(name) == b.counters.get(name), name


def test_hybrid_needs_no_vocab_capacity():
    # The whole point: arbitrary item ids without --num-items.
    cfg = Config(window_size=10, seed=2, skip_cuts=True, backend=Backend.HYBRID)
    users, items, ts = random_stream(32, n_items=500)
    job = run_production(cfg, users, items, ts)
    assert job.latest


def test_hybrid_checkpoint_roundtrip(tmp_path):
    from tpu_cooccurrence.job import CooccurrenceJob

    kw = dict(window_size=10, seed=4, item_cut=5, user_cut=3,
              backend=Backend.HYBRID, checkpoint_dir=str(tmp_path / "ck"),
              development_mode=True)
    users, items, ts = random_stream(33, n=400)
    half = 180

    ref = CooccurrenceJob(Config(**kw))
    ref.add_batch(users, items, ts)
    ref.finish()

    a = CooccurrenceJob(Config(**kw))
    a.add_batch(users[:half], items[:half], ts[:half])
    a.checkpoint()
    b = CooccurrenceJob(Config(**kw))
    b.restore()
    b.add_batch(users[half:], items[half:], ts[half:])
    b.finish()

    assert set(ref.latest) == set(b.latest)
    for item in ref.latest:
        np.testing.assert_allclose(
            np.array([s for _, s in b.latest[item]]),
            np.array([s for _, s in ref.latest[item]]), rtol=1e-6, atol=1e-6)
