"""The retired hybrid backend: ``--backend hybrid`` stays accepted as an
alias for the sparse backend.

Retired round 3 (VERDICT r2, Weak #2): on its flagship 1M-item Zipfian
config the sparse backend measured 2.2x the hybrid's on-chip throughput
(TPU_ROUND2.jsonl 2026-07-30) and serves the same beyond-dense-ceiling
vocabularies; checkpoints were interchangeable by design, so migration
is a no-op (see test_sparse.test_sparse_hybrid_checkpoint_interchange).
"""

from tpu_cooccurrence.config import Backend, Config
from tpu_cooccurrence.metrics import OBSERVED_COOCCURRENCES
from tpu_cooccurrence.state.sparse_scorer import SparseDeviceScorer

from test_pipeline import assert_latest_close, random_stream, run_production


def test_hybrid_alias_runs_sparse():
    kw = dict(window_size=10, seed=0xBEEF, item_cut=5, user_cut=4)
    users, items, ts = random_stream(31)
    a = run_production(Config(**kw, backend=Backend.ORACLE), users, items, ts)
    b = run_production(Config(**kw, backend=Backend.HYBRID), users, items, ts)
    assert isinstance(b.scorer, SparseDeviceScorer)
    assert_latest_close(a.latest, b.latest)
    assert (a.counters.get(OBSERVED_COOCCURRENCES)
            == b.counters.get(OBSERVED_COOCCURRENCES))


def test_hybrid_alias_needs_no_vocab_capacity():
    # The retired backend's selling point, preserved by the alias:
    # arbitrary item ids without --num-items.
    cfg = Config(window_size=10, seed=2, skip_cuts=True,
                 backend=Backend.HYBRID)
    users, items, ts = random_stream(32, n_items=500)
    job = run_production(cfg, users, items, ts)
    assert job.latest
