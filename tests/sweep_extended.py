"""Out-of-suite extended randomized sweep (run manually after major
changes — docs/ARCHITECTURE.md testing strategy):

    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python tests/sweep_extended.py [--trials 30] [--seed-base 0xA11CE]

Samples the config space (tumbling/sliding, cuts on/off, random top-k
including > vocab, random streams) and checks a wide backend-variant
matrix against the float64 oracle through the in-suite protocol
(identical counters; scores to tolerance; gap-gated exact ids). Round 4
provenance: seed family 0xA11CE caught the vocab-smaller-than-top-K
dense crash (fixed + pinned in tests/test_pipeline.py); families
0xA11CE and 0xB0B then ran 240 runs clean.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np  # noqa: E402


def _checkpoint_trial(trial, rng, kw, slide, users, items, ts,
                      assert_latest_close, Backend, Config):
    """Randomized mid-stream checkpoint/restore equivalence: restore at
    a random split point and finish — results must match an
    uninterrupted run for every backend."""
    import tempfile

    import numpy as np

    from tpu_cooccurrence.job import CooccurrenceJob

    split = int(len(users) * float(rng.uniform(0.3, 0.7)))
    fails = 0
    for backend, extra in (("oracle", {}), ("sparse", {}),
                           ("device", {}),
                           ("sparse", {"num_shards": 4})):
        with tempfile.TemporaryDirectory() as ck:
            cfg = Config(backend=Backend(backend), window_slide=slide,
                         development_mode=True, checkpoint_dir=ck,
                         **dict(kw, **extra))
            try:
                ref = CooccurrenceJob(Config(
                    backend=Backend(backend), window_slide=slide,
                    development_mode=True, **dict(kw, **extra)))
                ref.add_batch(users, items, ts)
                ref.finish()
                a = CooccurrenceJob(cfg)
                a.add_batch(users[:split], items[:split], ts[:split])
                a.checkpoint()
                b = CooccurrenceJob(cfg)
                b.restore()
                b.add_batch(users[split:], items[split:], ts[split:])
                b.finish()
                assert (ref.counters.as_dict() == b.counters.as_dict()
                        ), "counters diverge"
                r = {i: ref.latest[i] for i in ref.latest}
                g = {i: b.latest[i] for i in b.latest}
                assert set(r) == set(g), "item sets diverge"
                for item in r:
                    np.testing.assert_allclose(
                        np.array([v for _, v in g[item]]),
                        np.array([v for _, v in r[item]]),
                        rtol=1e-6, atol=1e-6)
            except Exception as exc:
                fails += 1
                print(f"CKPT TRIAL {trial} {backend} {extra} "
                      f"split={split}: {exc!r}"[:300], flush=True)
    return fails


def _multihost_trial(trial, rng, kw, slide, users, items, ts, tmpdir):
    """One randomized 2-process multi-controller run vs the in-process
    8-shard reference: merged disjoint row partitions must reproduce
    the single-process results exactly."""
    import json
    import socket
    import subprocess

    import numpy as np

    from tpu_cooccurrence.config import Backend, Config
    from test_pipeline import run_production

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = os.path.join(repo, "tests", "multihost_worker.py")
    backend = ["sharded", "sparse"][trial % 2]
    partition = bool(rng.integers(0, 2))
    n_items_cap = int(items.max()) + 1
    stream = os.path.join(tmpdir, f"s{trial}.npz")
    np.savez(stream, users=users, items=items, ts=ts)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        coordinator = f"127.0.0.1:{s.getsockname()[1]}"
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               XLA_FLAGS="--xla_force_host_platform_device_count=4",
               PYTHONPATH=repo + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    procs, outs = [], []
    for pid in range(2):
        spec = dict(kw, stream=stream, coordinator=coordinator,
                    num_processes=2, process_id=pid, phase="full",
                    backend=backend, num_shards=8, num_items=n_items_cap,
                    partition_sampling=partition, window_slide=slide)
        spec_p = os.path.join(tmpdir, f"spec{trial}-{pid}.json")
        out_p = os.path.join(tmpdir, f"out{trial}-{pid}.json")
        with open(spec_p, "w") as f:
            json.dump(spec, f)
        outs.append(out_p)
        procs.append(subprocess.Popen(
            [sys.executable, worker, spec_p, out_p], env=env, cwd=repo,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    results = []
    for p, out_p in zip(procs, outs):
        stdout, stderr = p.communicate(timeout=300)
        if p.returncode != 0:
            print(f"MH TRIAL {trial} {backend} ps={partition}: worker "
                  f"rc={p.returncode}: {stderr[-300:]}", flush=True)
            return 1
        with open(out_p) as f:
            results.append(json.load(f))
    merged = {}
    for res in results:
        for item, top in res["latest"].items():
            if int(item) in merged:
                print(f"MH TRIAL {trial}: row {item} from two processes",
                      flush=True)
                return 1
            merged[int(item)] = [(int(j), s) for j, s in top]
    ref = run_production(
        Config(**kw, backend=Backend(backend), num_shards=8,
               num_items=n_items_cap, window_slide=slide),
        users, items, ts)
    ok = set(merged) == set(ref.latest)
    if ok:
        for item in merged:
            a = np.array([v for _, v in merged[item]])
            b = np.array([v for _, v in ref.latest[item]])
            if len(a) != len(b) or not np.allclose(a, b, rtol=1e-6,
                                                   atol=1e-6):
                ok = False
                break
    if not ok:
        print(f"MH TRIAL {trial} {backend} ps={partition}: results "
              f"diverge from single-process reference", flush=True)
        return 1
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--trials", type=int, default=30)
    ap.add_argument("--seed-base", type=lambda s: int(s, 0),
                    default=0xA11CE)
    ap.add_argument("--checkpoint", action="store_true",
                    help="mid-stream checkpoint/restore equivalence "
                         "instead of the backend matrix")
    ap.add_argument("--multihost", action="store_true",
                    help="randomized 2-process multi-controller runs vs "
                         "the in-process reference")
    args = ap.parse_args()

    from tpu_cooccurrence.config import Backend, Config
    from test_pipeline import assert_latest_close, run_production

    fails = 0
    for trial in range(args.trials):
        rng = np.random.default_rng(args.seed_base + trial)
        n = int(rng.integers(200, 2500))
        n_users = int(rng.integers(2, 50))
        n_items = int(rng.integers(4, 200))
        users = rng.integers(0, n_users, n).astype(np.int64)
        items = rng.integers(0, n_items, n).astype(np.int64)
        ts = np.cumsum(rng.integers(0, 4, n)).astype(np.int64)
        kw = dict(window_size=int(rng.integers(3, 60)),
                  seed=int(rng.integers(0, 2**31)),
                  item_cut=int(rng.integers(1, 12)),
                  user_cut=int(rng.integers(1, 8)),
                  top_k=int(rng.integers(1, 14)),
                  skip_cuts=bool(rng.integers(0, 2)))
        slide = None
        if trial % 4 == 0:
            base = int(rng.integers(2, 10))
            kw["window_size"] = base * int(rng.integers(2, 5))
            slide = base
        if args.checkpoint:
            fails += _checkpoint_trial(trial, rng, kw, slide, users,
                                       items, ts, assert_latest_close,
                                       Backend, Config)
            if trial % 10 == 9:
                print(f"trial {trial + 1}/{args.trials} done", flush=True)
            continue
        if args.multihost:
            import tempfile

            # The worker spec carries neither of these; drop them from
            # the reference config too so both sides run identically.
            kw.pop("skip_cuts", None)
            kw.pop("top_k", None)
            with tempfile.TemporaryDirectory() as td:
                fails += _multihost_trial(trial, rng, kw, slide,
                                          users, items, ts, td)
            if trial % 5 == 4:
                print(f"trial {trial + 1}/{args.trials} done", flush=True)
            continue
        oracle = run_production(
            Config(backend=Backend.ORACLE, window_slide=slide,
                   development_mode=True, **kw), users, items, ts)
        ref = {i: oracle.latest[i] for i in oracle.latest}
        variants = [
            ("device", {"num_items": n_items}),
            ("device", {"num_items": n_items, "count_dtype": "int16"}),
            ("sparse", {}),
            ("sparse", {"num_shards": 8}),
            ("sparse", {"pallas": "on"}),
            ("sharded", {"num_items": n_items, "num_shards": 8}),
            ("sharded", {"num_shards": 4}),  # derive-from-data
        ]
        for backend, extra in variants:
            cfg = Config(backend=Backend(backend), window_slide=slide,
                         development_mode=True, **dict(kw, **extra))
            try:
                job = run_production(cfg, users, items, ts)
                assert job.counters.as_dict() == oracle.counters.as_dict()
                assert_latest_close(
                    ref, {i: job.latest[i] for i in job.latest},
                    rtol=2e-4, atol=2e-4)
            except Exception as exc:  # record all, fail at end
                fails += 1
                print(f"TRIAL {trial} {backend} {extra}: {exc!r}"[:300],
                      flush=True)
        if trial % 10 == 9:
            print(f"trial {trial + 1}/{args.trials} done", flush=True)
    print("FAILURES:", fails)
    return 1 if fails else 0


if __name__ == "__main__":
    sys.exit(main())
