"""LLR kernel tests.

Golden values are the Dunning-paper cases used by the reference test
(``LogLikelihoodTest.java:13-16``): 270.72, 263.90, 48.94 at tolerance 0.1.
"""

import numpy as np
import pytest

from tpu_cooccurrence.oracle.reference import _llr_scalar
from tpu_cooccurrence.ops import llr as llr_ops

GOLDEN = [
    ((110, 2442, 111, 29114), 270.72),
    ((29, 13, 123, 31612), 263.90),
    ((9, 12, 429, 31327), 48.94),
]


@pytest.mark.parametrize("cells,expected", GOLDEN)
def test_golden_scalar_oracle(cells, expected):
    assert _llr_scalar(*cells) == pytest.approx(expected, abs=0.1)


@pytest.mark.parametrize("cells,expected", GOLDEN)
def test_golden_numpy(cells, expected):
    assert llr_ops.llr_np(*cells) == pytest.approx(expected, abs=0.1)


@pytest.mark.parametrize("cells,expected", GOLDEN)
def test_golden_jax_stable_f32(cells, expected):
    vals = [np.float32(c) for c in cells]
    out = float(llr_ops.llr_stable_jit(*vals))
    assert out == pytest.approx(expected, abs=0.1)


def test_zero_cells():
    # Any zero cell must not produce NaN/inf (0*log 0 = 0 convention,
    # LogLikelihood.java:59-61).
    cases = [(0, 1, 2, 3), (1, 0, 2, 3), (1, 2, 0, 3), (1, 2, 3, 0),
             (0, 0, 0, 0), (5, 0, 0, 0), (0, 5, 0, 0)]
    for cells in cases:
        ref = _llr_scalar(*cells)
        assert np.isfinite(ref)
        got = float(llr_ops.llr_stable_jit(*[np.float32(c) for c in cells]))
        assert np.isfinite(got)
        assert got == pytest.approx(ref, abs=1e-3, rel=1e-4)


def test_independence_is_zero():
    # Perfectly independent table: LLR == 0 exactly.
    # rows (a+b, c+d), cols proportional: k11/k12 == k21/k22.
    assert _llr_scalar(10, 20, 100, 200) == pytest.approx(0.0, abs=1e-9)
    got = float(llr_ops.llr_stable_jit(*(np.float32(x) for x in (10, 20, 100, 200))))
    assert got == pytest.approx(0.0, abs=1e-3)


def test_stable_f32_matches_f64_oracle_at_scale():
    """The reason llr_stable exists: float32 accuracy at ~1e10 counts where
    the entropy form cancels catastrophically."""
    rng = np.random.default_rng(0xC0FFEE)
    n = 2000
    k11 = rng.integers(1, 500, n)
    r1 = k11 + rng.integers(0, 500_000, n)
    r2 = rng.integers(0, 1_000_000, n)
    k21 = np.minimum(rng.integers(0, 500_000, n), r2)
    observed = np.int64(30_000_000_000)
    k12 = r1 - k11
    k22 = observed + k11 - k12 - k21
    ref = llr_ops.llr_np(k11, k12, k21, k22)
    got = np.asarray(
        llr_ops.llr_stable_jit(
            k11.astype(np.float32), k12.astype(np.float32),
            k21.astype(np.float32), k22.astype(np.float32)))
    # Absolute tolerance on scores that range up to ~1e4.
    np.testing.assert_allclose(got, ref, rtol=5e-4, atol=5e-3)


def test_entropy_f32_would_fail_at_scale():
    """Documents why the entropy form is not used on device: in float32 it is
    garbage at large counts (sanity check that our reformulation is actually
    load-bearing)."""
    import jax.numpy as jnp

    cells = (200.0, 300_000.0, 400_000.0, 3e10)
    ref = float(llr_ops.llr_np(*cells))
    ent32 = float(llr_ops.llr_entropy(*(jnp.float32(c) for c in cells)))
    stable32 = float(llr_ops.llr_stable(*(jnp.float32(c) for c in cells)))
    assert abs(stable32 - ref) < 0.01 * max(1.0, abs(ref))
    assert abs(ent32 - ref) > abs(stable32 - ref)


def test_score_contingency_matches_reference_table():
    """k12/k21/k22 construction mirrors
    ItemRowRescorerTwoInputStreamOperator.java:230-241."""
    k11, rs_i, rs_j, obs = 7, 20, 15, 100
    expect = _llr_scalar(k11, rs_i - k11, rs_j - k11, obs + k11 - (rs_i - k11) - (rs_j - k11))
    got = float(llr_ops.score_contingency(
        np.float32(k11), np.float32(rs_i), np.float32(rs_j), np.float32(obs)))
    assert got == pytest.approx(expect, rel=1e-5, abs=1e-4)
