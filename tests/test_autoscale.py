"""Load-driven gang autoscaler (ISSUE 15): the unit layer.

Policy hysteresis/cooldown/bounds, the worker-side tap's packed gang
vote + pressure beacon + drain trigger, the supervisor's voluntary-exit
accounting over fake workers (a rescale is never a billed restart), the
topology-aware restore vote, the N→M blob merge, the scale-before-shed
precedence hold, and the observability surfaces (AUTOSCALE journal
records, gauges, the /healthz block). The real-CLI capstone — injected
load forcing 2→4, idle decaying 4→2, bit-identical stdout, and the
crash inside the rescale seam — is ``tests/test_autoscale_chaos.py``.
"""

import json
import os
import sys
import time

import numpy as np
import pytest

from tpu_cooccurrence.config import Backend, Config
from tpu_cooccurrence.observability.http import MetricsServer
from tpu_cooccurrence.observability.journal import (VERSION,
                                                    validate_record)
from tpu_cooccurrence.observability.registry import MetricsRegistry
from tpu_cooccurrence.robustness import faults
from tpu_cooccurrence.robustness.autoscale import (
    RESCALE_EXIT,
    AutoscaleTap,
    LadderScalePolicy,
    ScaleDecision,
    ScalePolicy,
    beacon_path,
    read_json,
    request_path,
    write_json,
)
from tpu_cooccurrence.robustness.degrade import (DegradationController,
                                                 DegradationLevel)
from tpu_cooccurrence.robustness.gang import (GangSupervisor,
                                              agree_restore_topology)
from tpu_cooccurrence.state import checkpoint as ckpt
from tpu_cooccurrence.state.store import merge_mh_cells, rebucket_cells


# -- LadderScalePolicy ---------------------------------------------------


def test_ladder_policy_grows_on_sustained_pressure():
    p = LadderScalePolicy(max_workers=8, min_workers=2, trip_windows=3,
                          clear_windows=8, cooldown_windows=0)
    assert p.decide(1, True, False, 1, 0, 2) is None
    assert p.decide(2, True, False, 2, 0, 2) is None
    d = p.decide(3, True, False, 3, 0, 2)
    assert (d.target, d.trigger, d.decision) == (4, "pressure", "grow")


def test_ladder_policy_shrinks_on_sustained_idle_and_clamps():
    p = LadderScalePolicy(max_workers=8, min_workers=2, trip_windows=3,
                          clear_windows=2, cooldown_windows=0)
    assert p.decide(1, False, True, 0, 1, 3) is None
    d = p.decide(2, False, True, 0, 2, 3)
    # 3 // 2 = 1 clamps to the min bound.
    assert (d.target, d.trigger, d.decision) == (2, "idle", "shrink")
    # At the floor the idle signal is a no-op.
    assert p.decide(3, False, True, 0, 3, 2) is None


def test_ladder_policy_caps_at_max_and_honors_cooldown():
    p = LadderScalePolicy(max_workers=4, min_workers=2, trip_windows=1,
                          clear_windows=1, cooldown_windows=2)
    assert p.decide(1, True, False, 1, 0, 2).target == 4
    # Cooldown: the next two observed windows are refractory even
    # though their signals would decide.
    assert p.decide(2, False, True, 0, 5, 4) is None
    assert p.decide(3, False, True, 0, 6, 4) is None
    assert p.decide(4, False, True, 0, 7, 4).target == 2
    # At max, pressure is the ladder's business, not the policy's.
    p2 = LadderScalePolicy(max_workers=4, min_workers=2, trip_windows=1,
                           clear_windows=1, cooldown_windows=0)
    assert p2.decide(1, True, False, 9, 0, 4) is None


def test_ladder_policy_cooldown_discards_warmup_evidence():
    """A warm-up that OUTLASTS the cooldown must not cascade a second
    rescale on its stale run counter: the decision needs its full trip
    run observed on post-cooldown windows (review fix)."""
    p = LadderScalePolicy(max_workers=8, min_workers=2, trip_windows=2,
                          clear_windows=2, cooldown_windows=2)
    assert p.decide(1, True, False, 1, 0, 2) is None
    assert p.decide(2, True, False, 2, 0, 2).target == 4
    # Windows 3-4: cooldown. Windows 5+: the worker's bad_run kept
    # climbing through the warm-up — but only post-cooldown windows
    # count, so window 5 (bad_run=5, fresh=1) must NOT decide...
    assert p.decide(3, True, False, 3, 0, 4) is None
    assert p.decide(4, True, False, 4, 0, 4) is None
    assert p.decide(5, True, False, 5, 0, 4) is None
    # ...and window 6 (two fresh overloaded windows) may.
    assert p.decide(6, True, False, 6, 0, 4).target == 8
    # Same for the idle side after that second cooldown.
    assert p.decide(7, False, True, 0, 9, 8) is None
    assert p.decide(8, False, True, 0, 10, 8) is None
    assert p.decide(9, False, True, 0, 11, 8) is None
    assert p.decide(10, False, True, 0, 12, 8).target == 4


def test_ladder_policy_dedupes_windows():
    p = LadderScalePolicy(max_workers=4, min_workers=2, trip_windows=1,
                          clear_windows=1, cooldown_windows=0)
    assert p.decide(5, False, False, 0, 0, 2) is None
    # Re-reading the same beacon window must not consume cooldown or
    # double-count anything.
    assert p.decide(5, True, False, 3, 0, 2) is None
    assert p.decide(6, True, False, 3, 0, 2).target == 4


def test_ladder_policy_validates_bounds():
    with pytest.raises(ValueError):
        LadderScalePolicy(max_workers=4, min_workers=1)
    with pytest.raises(ValueError):
        LadderScalePolicy(max_workers=2, min_workers=4)
    with pytest.raises(ValueError):
        LadderScalePolicy(max_workers=4, trip_windows=0)
    with pytest.raises(ValueError):
        LadderScalePolicy(max_workers=4, cooldown_windows=-1)
    with pytest.raises(ValueError):
        LadderScalePolicy(max_workers=4, factor=1)


# -- the worker-side tap -------------------------------------------------


def _tap(tmp_path, votes, pid=0, workers=2, idle_wall_s=0.1):
    gang = str(tmp_path / "gang")
    os.makedirs(gang, exist_ok=True)
    calls = []

    def exchange(v):
        calls.append(v)
        return votes.pop(0)

    tap = AutoscaleTap(gang, pid, workers, idle_wall_s=idle_wall_s,
                       exchange=exchange)
    return tap, gang, calls


def test_tap_packs_bits_and_counts_runs(tmp_path):
    tap, gang, calls = _tap(tmp_path, votes=[[1, 0], [0, 0], [2, 2]])
    # Overloaded window: bit 0 set locally; any peer bit -> gang over.
    assert tap.observe(1, wall_seconds=0.5, overloaded=True) is False
    assert calls[-1] & 1
    assert (tap.bad_run, tap.idle_run) == (1, 0)
    # Busy-but-healthy window (wall above the idle threshold): neither.
    assert tap.observe(2, wall_seconds=0.5, overloaded=False) is False
    assert calls[-1] == 0
    assert (tap.bad_run, tap.idle_run) == (0, 0)
    # Idle window: bit 1 set locally, AND-ed gang-wide.
    assert tap.observe(3, wall_seconds=0.01, overloaded=False) is False
    assert calls[-1] & 2
    assert (tap.bad_run, tap.idle_run) == (0, 1)
    beacon = read_json(beacon_path(gang, 0))
    assert beacon["window"] == 3 and beacon["idle"] == 1
    assert beacon["idle_run"] == 1 and beacon["bad_run"] == 0


def test_tap_idle_needs_every_worker(tmp_path):
    # Peer voted not-idle: the gang is not idle even though we are.
    tap, _gang, _ = _tap(tmp_path, votes=[[2, 0]])
    tap.observe(1, wall_seconds=0.01, overloaded=False)
    assert tap.idle_run == 0


def test_tap_overload_beats_idle(tmp_path):
    # A window can never be both: gang pressure zeroes the idle run.
    tap, _gang, _ = _tap(tmp_path, votes=[[2, 1]])
    tap.observe(1, wall_seconds=0.01, overloaded=False)
    assert (tap.bad_run, tap.idle_run) == (1, 0)


def test_tap_drains_only_on_unanimous_request_vote(tmp_path):
    tap, gang, calls = _tap(tmp_path, votes=[[4, 0], [4, 4]])
    req = {"to": 4, "from": 2, "decision": "grow",
           "trigger": "pressure", "window": 3, "cooldown": 2, "seq": 1}
    write_json(request_path(gang), req)
    # One peer has not seen the file yet: no drain this window.
    assert tap.observe(1, 0.5, overloaded=False) is False
    assert tap.drain is None
    assert calls[-1] & 4  # but we DID vote ready
    assert tap.observe(2, 0.5, overloaded=False) is True
    assert tap.drain == req


def test_tap_ignores_request_for_current_topology(tmp_path):
    tap, gang, calls = _tap(tmp_path, votes=[[7, 7]])
    write_json(request_path(gang), {"to": 2, "from": 2})
    # A stale request naming our own size must not arm the ready bit
    # (the peers' votes in `votes` are fabricated; ours is calls[-1]).
    tap.observe(1, 0.5, overloaded=False)
    assert not (calls[-1] & 4)


def test_tap_validates_idle_wall(tmp_path):
    with pytest.raises(ValueError):
        AutoscaleTap(str(tmp_path), 0, 2, idle_wall_s=0.0)


# -- scale-before-shed precedence ---------------------------------------


def test_hold_escalation_keeps_ladder_at_normal():
    c = DegradationController(window_wall_s=0.1, trip_windows=2,
                              clear_windows=2)
    c.hold_escalation = True
    for _ in range(5):
        c.observe_window(wall_seconds=1.0)
    assert c.level == DegradationLevel.NORMAL
    assert c.last_overloaded is True
    # At max capacity the job leaves the flag False: same signals shed.
    c.hold_escalation = False
    c.observe_window(wall_seconds=1.0)
    c.observe_window(wall_seconds=1.0)
    assert c.level == DegradationLevel.SHED_SAMPLING


def test_hold_never_blocks_deescalation():
    c = DegradationController(window_wall_s=0.1, trip_windows=1,
                              clear_windows=2)
    c.observe_window(wall_seconds=1.0)
    assert c.level == DegradationLevel.SHED_SAMPLING
    c.hold_escalation = True
    c.observe_window(wall_seconds=0.01)
    c.observe_window(wall_seconds=0.01)
    assert c.level == DegradationLevel.NORMAL


# -- journal + /healthz surfaces ----------------------------------------


def test_autoscale_journal_record_validates():
    validate_record({"v": VERSION, "autoscale": "grow", "from": 2,
                     "to": 4, "trigger": "pressure", "window": 7,
                     "cooldown": 8, "wall_unix": time.time()})
    with pytest.raises(ValueError, match="grow|shrink"):
        validate_record({"v": VERSION, "autoscale": "explode", "from": 2,
                         "to": 4, "trigger": "pressure", "window": 7,
                         "cooldown": 8, "wall_unix": 0.0})
    with pytest.raises(ValueError, match="pressure|idle"):
        validate_record({"v": VERSION, "autoscale": "grow", "from": 2,
                         "to": 4, "trigger": "vibes", "window": 7,
                         "cooldown": 8, "wall_unix": 0.0})
    with pytest.raises(ValueError, match="unknown"):
        validate_record({"v": VERSION, "autoscale": "grow", "from": 2,
                         "to": 4, "trigger": "idle", "window": 7,
                         "cooldown": 8, "wall_unix": 0.0, "extra": 1})


def test_healthz_autoscale_block():
    reg = MetricsRegistry()
    server = MetricsServer(reg)
    payload, _healthy = server.health()
    assert "autoscale" not in payload  # no tap armed
    reg.gauge("cooc_gang_target_workers").set(4)
    reg.gauge("cooc_gang_rescales_total").set(2)
    reg.gauge("cooc_autoscale_level").set(-1)
    payload, _healthy = server.health()
    assert payload["autoscale"] == {"target_workers": 4,
                                    "rescales_total": 2, "level": -1}
    server._server.server_close()


# -- the supervisor over fake workers ------------------------------------


FAKE_WORKER = r"""
import os, sys, time
args = sys.argv[1:]
def val(flag):
    return args[args.index(flag) + 1]
pid = int(val("--process-id"))
state_dir = val("-i")   # scratch dir smuggled as the input
mode = val("-ws")       # scenario name smuggled as the window size
gang_dir = os.environ["TPU_COOC_GANG_DIR"]
open(os.path.join(gang_dir, f"heartbeat.p{pid}"), "w").write("{}")
if mode == "mixed":
    # One worker finishes cleanly, the other takes the voluntary code
    # with no request pending — the mixed-verdict failure shape.
    if pid == 0:
        print("row-from-p0")
        sys.exit(0)
    sys.exit(86)
import json
req_path = os.path.join(gang_dir, "RESCALE")
deadline = time.time() + 1.2
window = 0
while time.time() < deadline:
    window += 1
    open(os.path.join(gang_dir, f"pressure.p{pid}.tmp"), "w").write(
        json.dumps({"window": window, "overloaded": 1, "idle": 0,
                    "bad_run": window, "idle_run": 0}))
    os.replace(os.path.join(gang_dir, f"pressure.p{pid}.tmp"),
               os.path.join(gang_dir, f"pressure.p{pid}"))
    if os.path.exists(req_path):
        if mode == "drain-crash" and pid == 0:
            marker = os.path.join(state_dir, "crashed-once")
            if not os.path.exists(marker):
                open(marker, "w").close()
                sys.exit(9)  # died INSIDE the seam
        sys.exit(86)  # voluntary rescale exit
    time.sleep(0.05)
print(f"row-from-p{pid}")
sys.exit(0)
"""


class ScriptedPolicy(ScalePolicy):
    """Deterministic decision feed for supervisor tests: pops the next
    target whenever a beacon window arrives, regardless of signals."""

    def __init__(self, targets):
        self.targets = list(targets)
        self.applied = []

    def decide(self, window, overloaded, idle, bad_run, idle_run,
               workers):
        if not self.targets:
            return None
        target = self.targets.pop(0)
        return ScaleDecision(target=target,
                             trigger=("pressure" if target > workers
                                      else "idle"),
                             window=window, cooldown=0)

    def rescaled(self, workers):
        self.applied.append(workers)


def _fake_gang(tmp_path, mode, policy, attempts=1):
    script = tmp_path / "fake_worker.py"
    script.write_text(FAKE_WORKER)

    class Sink:
        def __init__(self):
            self.text = ""

        def write(self, s):
            self.text += s

    sink = Sink()
    sup = GangSupervisor(
        ["-i", str(tmp_path), "-ws", mode], num_workers=2,
        attempts=attempts, gang_dir=str(tmp_path / "gang"),
        stale_after_s=0.0, delay_s=0.0, timeout_s=60.0,
        stdout=sink, python=[sys.executable, str(script)],
        scale_policy=policy)
    return sup, sink


def test_supervisor_rescales_never_consume_restart_budget(tmp_path):
    """The exit-code accounting satellite: a gang that rescales FIVE
    times on a budget of one restart never aborts — voluntary exits
    are free, and the final clean attempt's output forwards intact."""
    policy = ScriptedPolicy([4, 2, 4, 2, 4])
    sup, sink = _fake_gang(tmp_path, "rescale", policy, attempts=1)
    assert sup.run() == 0
    assert sup.rescales == 5
    assert policy.applied == [4, 2, 4, 2, 4]
    assert sup.num_workers == 4  # the last applied topology
    # Only the final (clean, 4-worker) attempt's spools forward.
    assert sink.text == ("row-from-p0\nrow-from-p1\n"
                         "row-from-p2\nrow-from-p3\n")
    # The request beacon never outlives its rescale.
    assert not os.path.exists(request_path(str(tmp_path / "gang")))


def test_supervisor_seam_crash_bills_budget_and_keeps_target(tmp_path):
    """A worker crashing between the drain decision and the relaunch is
    a REAL failure (one billed restart) — but the pending target is
    still honored, because the topology-aware restore vote restores
    whatever topology last committed at whatever size we relaunch."""
    policy = ScriptedPolicy([4])
    sup, sink = _fake_gang(tmp_path, "drain-crash", policy, attempts=1)
    assert sup.run() == 0
    assert sup.rescales == 0       # the drain never completed cleanly
    assert sup.num_workers == 4    # the target applied anyway
    assert policy.applied == [4]
    assert "row-from-p3" in sink.text


def test_supervisor_seam_crash_with_no_budget_aborts(tmp_path):
    policy = ScriptedPolicy([4])
    sup, _sink = _fake_gang(tmp_path, "drain-crash", policy, attempts=0)
    assert sup.run() == 9


def test_supervisor_mixed_verdict_never_exits_86(tmp_path):
    """Mixed clean/RESCALE_EXIT codes are a failed attempt, and the
    failure must never surface as 86 — that code is the voluntary
    contract and automation keys on it (review fix)."""
    sup, _sink = _fake_gang(tmp_path, "mixed", None, attempts=0)
    rc = sup.run()
    assert rc == 1
    assert rc != RESCALE_EXIT
    assert sup.rescales == 0


def test_supervisor_fires_rescale_relaunch_site(tmp_path):
    plan = faults.arm(["rescale_relaunch:exception"])
    try:
        policy = ScriptedPolicy([4])
        sup, _sink = _fake_gang(tmp_path, "rescale", policy, attempts=1)
        with pytest.raises(faults.InjectedFault):
            sup.run()
        assert plan.specs[0].fired
    finally:
        faults.disarm()


def test_supervisor_clears_stale_beacons_on_spawn(tmp_path):
    gang = tmp_path / "gang"
    gang.mkdir()
    # A decayed gang's retired slot left its beacon behind; the next
    # spawn must clear it so the policy never reads a ghost signal.
    write_json(str(gang / "pressure.p7"), {"window": 99})
    sup, _sink = _fake_gang(tmp_path, "clean-noop", None, attempts=0)
    workers = sup._spawn(0, 0, 0.0)
    assert not os.path.exists(gang / "pressure.p7")
    # Reap the fake workers (they exit 0 on their own within ~3s).
    for w in workers:
        w.proc.wait(timeout=30)
        w.spool.close()


# -- topology-aware restore vote ----------------------------------------


def _commit_gen(d, pid, gen, writers, marker=True, legacy=False):
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, f"state.p{pid}.{gen}.npz"), "wb") as f:
        f.write(b"x")
    if marker:
        with open(os.path.join(d, f"EPOCH.p{pid}.{gen}"), "w") as f:
            f.write(f"{gen}\n" if legacy else f"{gen} {writers}\n")


def test_topology_committed_generations(tmp_path):
    d = str(tmp_path / "ck")
    # gen 1: fully committed by a 2-process topology.
    for pid in (0, 1):
        _commit_gen(d, pid, 1, 2)
    # gen 2: only worker 0 marked (torn global commit).
    _commit_gen(d, 0, 2, 2)
    _commit_gen(d, 1, 2, 2, marker=False)
    assert ckpt.topology_committed_generations(d) == [(1, 2)]
    # gen 3: fully committed by a 4-process topology (post-rescale).
    for pid in range(4):
        _commit_gen(d, pid, 3, 4)
    assert ckpt.topology_committed_generations(d) == [(3, 4), (1, 2)]


def test_topology_vote_ignores_legacy_markers(tmp_path):
    d = str(tmp_path / "ck")
    for pid in (0, 1):
        _commit_gen(d, pid, 1, 2, legacy=True)
    assert ckpt.topology_committed_generations(d) == []


def test_topology_vote_is_chain_aware(tmp_path):
    d = str(tmp_path / "ck")
    for pid in (0, 1):
        _commit_gen(d, pid, 1, 2)
        _commit_gen(d, pid, 2, 2)
        # gen 2 is incremental for p0 but its base npz is gone: the
        # whole generation must not count.
        with open(os.path.join(d, "delta.p0.2.bin"), "wb") as f:
            f.write(b"d")
    os.remove(os.path.join(d, "state.p0.1.npz"))
    assert ckpt.topology_committed_generations(d) == []


def test_agree_restore_topology_quarantines_all_suffixes(tmp_path):
    d = str(tmp_path / "ck")
    for pid in (0, 1):
        _commit_gen(d, pid, 1, 2)
    # Torn newer generation on BOTH suffixes plus a retired-topology
    # straggler: the vote sweeps them all aside.
    _commit_gen(d, 0, 2, 2, marker=False)
    _commit_gen(d, 1, 2, 2, marker=False)
    _commit_gen(d, 3, 2, 4, marker=False)
    barriers = []
    agreed, writers = agree_restore_topology(
        d, process_id=0, exchange=lambda v: v,
        barrier=barriers.append)
    assert (agreed, writers) == (1, 2)
    assert barriers  # peers rendezvous after the sweep
    partials = sorted(n for n in os.listdir(d) if n.endswith(".partial"))
    assert partials == ["state.p0.2.npz.partial",
                        "state.p1.2.npz.partial",
                        "state.p3.2.npz.partial"]


def test_agree_restore_topology_refuses_legacy_markers(tmp_path):
    """Upgrade hazard: pre-autoscale markers carry no topology, and
    guessing it from marker counts would qualify a torn legacy commit
    — the vote refuses loudly instead of quarantining committed
    state."""
    d = str(tmp_path / "ck")
    for pid in (0, 1):
        _commit_gen(d, pid, 1, 2, legacy=True)
    assert ckpt.has_legacy_epoch_markers(d)
    with pytest.raises(ValueError, match="pre-autoscale"):
        agree_restore_topology(d, process_id=0, exchange=lambda v: v,
                               barrier=lambda n: None)
    # Nothing was touched.
    assert not any(n.endswith(".partial") for n in os.listdir(d))


def test_agree_restore_topology_refuses_markerless_state(tmp_path):
    """Pre-epoch legacy layout: per-process generation files with NO
    markers at all hold committed state the fixed-topology vote would
    restore — the topology vote must refuse, not quarantine it all
    (review fix)."""
    d = str(tmp_path / "ck")
    for pid in (0, 1):
        _commit_gen(d, pid, 1, 2, marker=False)
    assert not ckpt.has_epoch_markers(d)
    with pytest.raises(ValueError, match="no epoch markers"):
        agree_restore_topology(d, process_id=0, exchange=lambda v: v,
                               barrier=lambda n: None)
    assert not any(n.endswith(".partial") for n in os.listdir(d))
    # A genuinely torn history (SOME new-format markers, none complete)
    # still proceeds to the quarantine: recovery, not refusal.
    _commit_gen(d, 0, 2, 2)  # p0 marked gen 2; p1 never did
    agreed, writers = agree_restore_topology(
        d, process_id=0, exchange=lambda v: v, barrier=lambda n: None)
    assert (agreed, writers) == (-1, 0)
    assert any(n.endswith(".partial") for n in os.listdir(d))


def test_supervisor_broken_policy_aborts_the_gang(tmp_path):
    """A policy that raises must abort the run loudly: the workers hold
    the shed ladder on the promise of rescaling, so a supervisor that
    quietly dropped its policy would leave sustained overload with no
    relief of either kind (review fix)."""

    class BrokenPolicy(ScalePolicy):
        def decide(self, *a):
            raise RuntimeError("boom")

    sup, _sink = _fake_gang(tmp_path, "rescale", BrokenPolicy(),
                            attempts=1)
    with pytest.raises(RuntimeError, match="boom"):
        sup.run()


def test_agree_restore_topology_stale_view_fails_loudly(tmp_path):
    """The gang agreed on a generation this host cannot see (stale
    directory view): fail the attempt with a transient error — never
    limp into a zero-writer restore (review fix)."""
    d = str(tmp_path / "ck")
    for pid in (0, 1):
        _commit_gen(d, pid, 2, 2)
    with pytest.raises(RuntimeError, match="cannot see"):
        agree_restore_topology(d, process_id=1,
                               exchange=lambda v: 1,  # peers voted 1
                               barrier=lambda n: None)


def test_agree_restore_topology_fresh_dir(tmp_path):
    d = str(tmp_path / "ck")
    agreed, writers = agree_restore_topology(
        d, process_id=1, exchange=lambda v: v, barrier=lambda n: None)
    assert (agreed, writers) == (-1, 0)


def test_process_suffixes(tmp_path):
    d = str(tmp_path / "ck")
    os.makedirs(d)
    for name in ("state.p0.3.npz", "delta.p2.4.bin", "state.1.npz",
                 "state.p1.2.npz.partial"):
        open(os.path.join(d, name), "w").close()
    assert ckpt.process_suffixes(d) == [".p0", ".p2"]


# -- the N→M blob merge --------------------------------------------------


def _mh_blobs(keys, cnt, d_old, owners_by_file):
    """Split a global (keys, cnt) blob into fake per-process mh blobs
    exactly the way _device_checkpoint_state lays them out."""
    owner = (keys >> 32) % d_old
    blobs = []
    rs = np.arange(100, dtype=np.int64)
    for shards in owners_by_file:
        parts = [cnt[owner == d] for d in shards]
        blobs.append({
            "mh_rows_key": keys,
            "mh_local_shards": np.asarray(shards, dtype=np.int64),
            "mh_local_cnt": (np.concatenate(parts).astype(np.int64)
                             if parts else np.zeros(0, np.int64)),
            "row_sums": rs,
            "observed": np.asarray([1234], dtype=np.int64),
        })
    return blobs


def test_merge_mh_cells_reassembles_the_global_blob():
    rng = np.random.default_rng(7)
    rows = rng.integers(0, 60, 200).astype(np.int64)
    dst = rng.integers(0, 60, 200).astype(np.int64)
    keys = np.unique((rows << 32) | dst)
    cnt = rng.integers(1, 90, len(keys)).astype(np.int64)
    merged = merge_mh_cells(_mh_blobs(keys, cnt, 2, [[0], [1]]))
    assert np.array_equal(merged["rows_key"], keys)
    assert np.array_equal(merged["rows_cnt"], cnt)
    assert merged["observed"][0] == 1234
    # Multi-shard-per-process layouts too (2 processes x 2 shards).
    merged4 = merge_mh_cells(_mh_blobs(keys, cnt, 4, [[0, 1], [2, 3]]))
    assert np.array_equal(merged4["rows_key"], keys)
    assert np.array_equal(merged4["rows_cnt"], cnt)
    # The merged blob round-trips through the rescale re-bucket.
    parts = rebucket_cells(merged["rows_key"], merged["rows_cnt"], 3)
    assert sum(len(lk) for lk, _v, _d in parts) == len(keys)


def test_merge_mh_cells_keeps_zero_cells_like_mh_restore():
    """A zeroed cell still owns its slot: the same-topology mh restore
    keeps it, so the cross-topology merge must too — dropping it would
    shift every later re-insertion's slot-ordered tie-breaks."""
    keys = np.asarray([(1 << 32) | 2, (2 << 32) | 3], dtype=np.int64)
    cnt = np.asarray([5, 0], dtype=np.int64)
    merged = merge_mh_cells(_mh_blobs(keys, cnt, 2, [[0], [1]]))
    assert np.array_equal(merged["rows_key"], keys)
    assert np.array_equal(merged["rows_cnt"], cnt)


def test_merge_mh_cells_rejects_missing_writer():
    keys = np.asarray([(1 << 32) | 2], dtype=np.int64)
    cnt = np.asarray([5], dtype=np.int64)
    blobs = _mh_blobs(keys, cnt, 2, [[1]])  # shard 0's file missing
    with pytest.raises(ValueError, match="missing"):
        merge_mh_cells(blobs)


# -- config gating -------------------------------------------------------


def _auto_cfg(**kw):
    base = dict(window_size=10, backend=Backend.SPARSE, num_shards=2,
                gang_workers=2, degrade=True, checkpoint_dir="/tmp/ck",
                autoscale="on", autoscale_max_workers=4)
    base.update(kw)
    return Config(**base)


def test_autoscale_config_gating():
    _auto_cfg()  # the valid shape
    with pytest.raises(ValueError, match="off.on"):
        _auto_cfg(autoscale="maybe")
    with pytest.raises(ValueError, match="gang"):
        _auto_cfg(gang_workers=0)
    with pytest.raises(ValueError, match="degrade"):
        _auto_cfg(degrade=False)
    with pytest.raises(ValueError, match="checkpoint-dir"):
        _auto_cfg(checkpoint_dir=None)
    with pytest.raises(ValueError, match="sparse"):
        _auto_cfg(backend=Backend.SHARDED, num_items=64)
    with pytest.raises(ValueError, match="max-workers"):
        _auto_cfg(autoscale_max_workers=0)
    with pytest.raises(ValueError, match=">= 2"):
        _auto_cfg(autoscale_min_workers=1)
    with pytest.raises(ValueError, match="launch topology"):
        _auto_cfg(gang_workers=8)
    with pytest.raises(ValueError, match="trip"):
        _auto_cfg(autoscale_trip_windows=0)
    with pytest.raises(ValueError, match="cooldown"):
        _auto_cfg(autoscale_cooldown_windows=-1)
    # Worker-side shape (the supervisor strips --gang-workers and
    # assigns the multi-controller identity).
    Config(window_size=10, backend=Backend.SPARSE, num_shards=2,
           degrade=True, checkpoint_dir="/tmp/ck", autoscale="on",
           autoscale_max_workers=4, coordinator="127.0.0.1:9",
           num_processes=2, process_id=0)


def test_autoscale_off_is_inert():
    # The default never constrains anything else.
    Config(window_size=10, autoscale_max_workers=0)


def test_rescale_sites_registered():
    assert "rescale_drain" in faults.SITES
    assert "rescale_relaunch" in faults.SITES
