"""Fault-injection plane: spec grammar, exactly-once semantics, kinds,
and the static consistency of site names across the repo."""

import os
import time

import pytest

from tpu_cooccurrence.robustness import faults
from tpu_cooccurrence.robustness.faults import (
    FaultPlan,
    FaultSpec,
    InjectedFault,
    UnknownFaultSiteError,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- spec grammar ------------------------------------------------------


def test_parse_defaults():
    s = FaultSpec.parse("window_fire", 0)
    assert (s.site, s.window_seq, s.kind, s.arg) == (
        "window_fire", None, "crash", None)


def test_parse_full():
    s = FaultSpec.parse("scorer_dispatch:7:delay_ms:1500", 3)
    assert (s.site, s.window_seq, s.kind, s.arg, s.index) == (
        "scorer_dispatch", 7, "delay_ms", 1500, 3)


def test_parse_kind_without_seq():
    s = FaultSpec.parse("journal_append:torn_write", 0)
    assert (s.site, s.window_seq, s.kind) == (
        "journal_append", None, "torn_write")


@pytest.mark.parametrize("bad, match", [
    ("no_such_site", "unknown fault site"),
    ("window_fire:3:no_such_kind", "unknown fault kind"),
    ("window_fire:0", "window_seq must be >= 1"),
    ("window_fire:3:delay_ms", "needs an argument"),
    ("window_fire:3:crash:42", "takes no argument"),
    ("window_fire:3:delay_ms:oops", "one integer argument"),
    ("window_fire:3:delay_ms:-50", "non-negative"),
])
def test_parse_rejects(bad, match):
    with pytest.raises(ValueError, match=match):
        FaultSpec.parse(bad, 0)


def test_config_validates_specs_at_parse_time():
    from tpu_cooccurrence.config import Config

    with pytest.raises(UnknownFaultSiteError, match="registered sites"):
        Config(input="x", window_size=10, seed=1,
               inject_fault=["bogus_site:crash"])  # cooclint: disable=fault-site


# -- firing semantics --------------------------------------------------


def test_exception_kind_fires_once_at_seq():
    plan = FaultPlan.parse(["window_fire:3:exception"])
    plan.fire("window_fire", seq=1)
    plan.fire("window_fire", seq=2)
    plan.fire("scorer_dispatch", seq=3)  # wrong site: no trigger
    with pytest.raises(InjectedFault, match="window_fire"):
        plan.fire("window_fire", seq=3)
    plan.fire("window_fire", seq=4)  # spent: never re-fires


def test_seq_trigger_is_at_least_not_exact():
    """A spec armed for seq 3 must still fire if the site is first hit
    at seq 5 (e.g. the checkpoint cadence skipped the exact ordinal)."""
    plan = FaultPlan.parse(["checkpoint_pre_write:3:exception"])
    with pytest.raises(InjectedFault):
        plan.fire("checkpoint_pre_write", seq=5)


def test_delay_kind_sleeps(monkeypatch):
    naps = []
    monkeypatch.setattr(time, "sleep", naps.append)
    plan = FaultPlan.parse(["source_read:delay_ms:2500"])
    plan.fire("source_read", seq=1)
    assert naps == [2.5]


def test_crash_kind_calls_die(monkeypatch):
    deaths = []
    monkeypatch.setattr(faults, "_die", lambda: deaths.append(True))
    plan = FaultPlan.parse(["window_fire"])
    plan.fire("window_fire", seq=1)
    assert deaths == [True]


def test_torn_write_truncates_and_renames(tmp_path, monkeypatch):
    monkeypatch.setattr(faults, "_die", lambda: None)
    staged = tmp_path / "snap.tmp"
    staged.write_bytes(b"x" * 1000)
    final = tmp_path / "state.1.npz"
    plan = FaultPlan.parse(["checkpoint_post_write:torn_write"])
    plan.fire("checkpoint_post_write", seq=1, path=str(staged),
              rename_to=str(final))
    assert not staged.exists()
    assert final.stat().st_size == 500  # torn half committed in place


def test_torn_write_append_shape(tmp_path, monkeypatch):
    monkeypatch.setattr(faults, "_die", lambda: None)
    j = tmp_path / "j.jsonl"
    j.write_text('{"seq": 1}\n')
    plan = FaultPlan.parse(["journal_append:torn_write"])
    plan.fire("journal_append", seq=2, path=str(j))
    text = j.read_text()
    assert text.startswith('{"seq": 1}\n')  # history intact
    assert not text.endswith("\n")  # torn, newline-less tail


def test_state_dir_persists_fired_across_rearm(tmp_path, monkeypatch):
    deaths = []
    monkeypatch.setattr(faults, "_die", lambda: deaths.append(True))
    sd = str(tmp_path / "fault-state")
    plan = FaultPlan.parse(["window_fire:2"], state_dir=sd)
    plan.fire("window_fire", seq=2)
    assert deaths == [True]
    # A "restarted" process re-arms the same specs: the marker written
    # before the kill keeps the spec spent.
    plan2 = FaultPlan.parse(["window_fire:2"], state_dir=sd)
    assert plan2.specs[0].fired
    plan2.fire("window_fire", seq=2)
    assert deaths == [True]


def test_arm_disarm_module_plan():
    try:
        p = faults.arm(["window_fire:99:exception"])
        assert faults.PLAN is p
    finally:
        faults.disarm()
    assert faults.PLAN is None


# -- static consistency ------------------------------------------------


def test_every_referenced_site_name_is_registered():
    """Site names cannot drift: every fault-site reference anywhere in
    the repo (fire() call sites, --inject-fault examples in docs/tests,
    spec strings) must be a key of SITES — and every registered site
    must actually be fired somewhere in the package (no dead entries).

    Thin wrapper over cooclint's ``fault-site`` rule
    (``tpu_cooccurrence.analysis.rules_registry``) so there is exactly
    one implementation of the scan; deliberately-bad site names in
    tests carry per-line ``# cooclint: disable=fault-site`` markers.
    """
    from tpu_cooccurrence.analysis import Analyzer, RULES

    result = Analyzer(REPO, rules=[RULES["fault-site"]]).run()
    assert not result.findings, "\n".join(map(str, result.findings))


def test_supervised_injection_requires_state_dir():
    from tpu_cooccurrence.config import Config

    with pytest.raises(ValueError, match="fault-state-dir"):
        Config(input="x", window_size=10, seed=1,
               restart_on_failure=2,
               inject_fault=["window_fire:3:crash"])
    # Fine with the marker dir (and fine unsupervised without one).
    Config(input="x", window_size=10, seed=1, restart_on_failure=2,
           inject_fault=["window_fire:3:crash"], fault_state_dir="/tmp/fs")
    Config(input="x", window_size=10, seed=1,
           inject_fault=["window_fire:3:crash"])


# -- process-qualified specs (site@proc, the gang chaos grammar) -------


def test_parse_process_qualifier():
    s = FaultSpec.parse("ckpt_commit@1:5:crash", 0)
    assert (s.site, s.proc, s.window_seq, s.kind) == (
        "ckpt_commit", 1, 5, "crash")
    # Unqualified spec: proc stays None (fires in any process).
    assert FaultSpec.parse("ckpt_commit:5:crash", 0).proc is None


@pytest.mark.parametrize("bad", ["ckpt_commit@:5", "ckpt_commit@x:5",
                                 "ckpt_commit@-1:5"])
def test_parse_rejects_bad_process_qualifier(bad):
    with pytest.raises(ValueError, match="process qualifier"):
        FaultSpec.parse(bad, 0)


def test_qualified_spec_fires_only_in_matching_process():
    plan = FaultPlan.parse(["barrier_enter@1:exception"], process_id=0)
    plan.fire("barrier_enter", seq=1)  # wrong process: no trigger
    assert not plan.specs[0].fired
    plan = FaultPlan.parse(["barrier_enter@1:exception"], process_id=1)
    with pytest.raises(InjectedFault):
        plan.fire("barrier_enter", seq=1)


def test_unqualified_plan_arms_as_process_zero():
    # A plan armed without a process id is process 0: @0 fires, @1 not.
    plan = FaultPlan.parse(["peer_heartbeat@0:exception"])
    with pytest.raises(InjectedFault):
        plan.fire("peer_heartbeat", seq=1)
    plan = FaultPlan.parse(["peer_heartbeat@1:exception"])
    plan.fire("peer_heartbeat", seq=1)
    assert not plan.specs[0].fired


def test_fired_markers_are_per_process_in_shared_state_dir(tmp_path):
    """Gang workers share one --fault-state-dir: each process's
    exactly-once is tracked independently (fault<i>.p<pid>.fired)."""
    d = str(tmp_path / "fs")
    p0 = FaultPlan.parse(["window_fire:exception"], state_dir=d,
                         process_id=0)
    with pytest.raises(InjectedFault):
        p0.fire("window_fire", seq=1)
    assert os.path.exists(os.path.join(d, "fault0.p0.fired"))
    # Process 1 arming from the same dir is NOT pre-fired by p0's
    # marker, and records its own on firing.
    p1 = FaultPlan.parse(["window_fire:exception"], state_dir=d,
                         process_id=1)
    assert not p1.specs[0].fired
    with pytest.raises(InjectedFault):
        p1.fire("window_fire", seq=1)
    assert os.path.exists(os.path.join(d, "fault0.p1.fired"))
    # A restarted process 0 sees only its own marker: spent.
    p0b = FaultPlan.parse(["window_fire:exception"], state_dir=d,
                          process_id=0)
    assert p0b.specs[0].fired
