"""CLI driver: end-to-end runs and crash recovery (in-process main)."""

import numpy as np

from tpu_cooccurrence import cli


def write_stream(path, seed=0, n=600, ts_offset=0):
    rng = np.random.default_rng(seed)
    ts = ts_offset + np.cumsum(rng.integers(0, 3, n))
    with open(path, "w") as f:
        for u, i, t in zip(rng.integers(0, 20, n),
                           rng.integers(100, 140, n), ts):
            f.write(f"{u},{i},{t}\n")


def run_cli(capsys, *argv):
    rc = cli.main(list(argv))
    assert rc == 0
    return capsys.readouterr().out


def test_cli_unregistered_fault_site_exits_2_with_site_list(
        caplog, tmp_path):
    """A typo'd --inject-fault site is exit code 2 (in the supervisor's
    PERMANENT_EXIT_CODES — never retried) and the error names the
    registered sites so the operator can fix the spec blind."""
    from tpu_cooccurrence.robustness.faults import SITES

    f = tmp_path / "in.csv"
    write_stream(f, n=50)
    rc = cli.main(["-i", str(f), "-ws", "50", "--backend", "oracle",
                   "--inject-fault", "not_a_site:3:crash"])  # cooclint: disable=fault-site
    assert rc == 2
    err = "\n".join(r.getMessage() for r in caplog.records)
    assert "not_a_site" in err
    for site in SITES:
        assert site in err  # the full registered list is quoted
    # Other config errors keep the EX_CONFIG (78) classification.
    rc = cli.main(["-i", str(f), "-ws", "50", "--backend", "oracle",
                   "--inject-fault", "window_fire:3:delay_ms"])
    assert rc == 78


def test_cli_oracle_end_to_end(capsys, tmp_path):
    f = tmp_path / "in.csv"
    write_stream(f)
    out = run_cli(capsys, "-i", str(f), "-ws", "50", "--backend", "oracle",
                  "-s", "0xC0FFEE")
    lines = [l for l in out.splitlines() if l]
    assert lines, "expected per-item result lines"
    item, rest = lines[0].split("\t")
    scores = [float(t.split(":")[1]) for t in rest.split()]
    assert scores == sorted(scores, reverse=True)


def test_cli_restores_checkpoint_and_skips_consumed_input(capsys, tmp_path):
    f = tmp_path / "in.csv"
    write_stream(f)
    ckpt = tmp_path / "ckpt"
    base = ["-i", str(f), "-ws", "50", "--backend", "oracle", "-s", "7",
            "--checkpoint-dir", str(ckpt), "--checkpoint-every-windows", "1"]
    out1 = run_cli(capsys, *base)
    assert list(ckpt.glob("state.*.npz")), "no checkpoint generation landed"

    # Second invocation: restores (including the source offset), finds no
    # new input, and reproduces the same results.
    out2 = run_cli(capsys, *base)
    assert out2 == out1


def test_cli_restore_continues_with_new_files(capsys, tmp_path):
    d = tmp_path / "stream"
    d.mkdir()
    write_stream(d / "a.csv", seed=1)
    ckpt = tmp_path / "ckpt"
    base = ["-i", str(d), "-ws", "50", "--backend", "oracle", "-s", "9",
            "--checkpoint-dir", str(ckpt), "--checkpoint-every-windows", "1"]
    run_cli(capsys, *base)
    n_splits_1 = 1

    # A new file arrives whose event time continues the stream; the
    # restored run must consume only it (and fire new windows, which
    # refreshes the periodic checkpoint).
    write_stream(d / "b.csv", seed=2, ts_offset=2_000)
    import json

    run_cli(capsys, *base)
    meta = json.loads((ckpt / "meta.json").read_text())
    assert meta["counters"]["SplitReaderNumSplits"] == n_splits_1 + 1
    assert meta["counters"].get("UserInteractionCounterLateElements", 0) == 0


def test_midfile_checkpoint_resumes_exactly(tmp_path):
    """A checkpoint taken while a file is partially ingested must resume at
    the exact line, not re-ingest or drop the tail (the reference's marker
    is whole-file only — this closes that gap)."""
    from tpu_cooccurrence.config import Backend, Config
    from tpu_cooccurrence.io.parse import batched_lines
    from tpu_cooccurrence.io.source import FileMonitorSource
    from tpu_cooccurrence.job import CooccurrenceJob

    f = tmp_path / "in.csv"
    write_stream(f, seed=5, n=900)
    cfg = lambda: Config(window_size=50, seed=11, backend=Backend.ORACLE,
                         checkpoint_dir=str(tmp_path / "ck"))

    # Uninterrupted reference run.
    ref = CooccurrenceJob(cfg())
    src = FileMonitorSource(str(f), ref.counters)
    ref.run(batched_lines(src.lines()))

    # Run A: consume a few small batches, checkpoint mid-file, "crash".
    a = CooccurrenceJob(cfg())
    src_a = FileMonitorSource(str(f), a.counters)
    batches = batched_lines(src_a.lines(), batch_size=200)
    for _ in range(2):
        a.add_batch(*next(batches))
    a.checkpoint(source=src_a)

    # Run B: restore and continue to the end.
    b = CooccurrenceJob(cfg())
    src_b = FileMonitorSource(str(f), b.counters)
    b.restore(source=src_b)
    for batch in batched_lines(src_b.lines(), batch_size=200):
        b.add_batch(*batch)
    b.finish()

    assert set(ref.latest) == set(b.latest)
    for item in ref.latest:
        assert ref.latest[item] == b.latest[item], item
    for name, val in ref.counters.as_dict().items():
        if name != "SplitReaderNumSplits":  # split re-listed once on resume
            assert b.counters.as_dict()[name] == val, name


def test_cli_emit_updates_streams_and_final_state_matches(capsys, tmp_path):
    """--emit-updates streams one line per updated row per window; the
    LAST update of each item must equal the default final dump."""
    f = tmp_path / "in.csv"
    write_stream(f)
    final = run_cli(capsys, "-i", str(f), "-ws", "50", "--backend",
                    "oracle", "-s", "0xC0FFEE")
    stream = run_cli(capsys, "-i", str(f), "-ws", "50", "--backend",
                     "oracle", "-s", "0xC0FFEE", "--emit-updates")
    stream_lines = [l for l in stream.splitlines() if l]
    final_lines = sorted(l for l in final.splitlines() if l)
    # More updates than items (items rescore across windows)...
    assert len(stream_lines) > len(final_lines)
    # ...and the last streamed row per item is exactly the final state.
    last = {}
    for line in stream_lines:
        last[line.split("\t")[0]] = line
    assert sorted(last.values()) == final_lines


def test_cli_emit_updates_replays_restored_state(capsys, tmp_path):
    """A resumed --emit-updates run replays the restored rows so the
    stream is complete even for items never re-updated after resume."""
    f = tmp_path / "in.csv"
    write_stream(f)
    ck = str(tmp_path / "ck")
    base = ["-i", str(f), "-ws", "50", "--backend", "oracle",
            "-s", "0xC0FFEE", "--checkpoint-dir", ck]
    final = run_cli(capsys, *base, "--checkpoint-every-windows", "2")
    # Second run: input fully consumed, nothing new fires — the stream
    # must still carry the full restored state.
    stream = run_cli(capsys, *base, "--emit-updates")
    last = {}
    for line in (l for l in stream.splitlines() if l):
        last[line.split("\t")[0]] = line
    assert sorted(last.values()) == sorted(l for l in final.splitlines() if l)


def test_cli_sigkill_resume_bit_identical(tmp_path):
    """A real crash: SIGKILL the CLI mid-run (after its first periodic
    checkpoint lands), rerun the same command, and require byte-identical
    stdout to an uninterrupted run — the fault-tolerance property the
    reference cannot offer (its rescorer state dies with the JVM)."""
    import os
    import signal
    import subprocess
    import sys
    import time

    f = tmp_path / "in.csv"
    # 30k events: the SIGKILL lands right after the FIRST periodic
    # checkpoint (the glob loop below), so the stream tail past that
    # point only buys wall time, not coverage — half the events still
    # leave ~3/4 of the run to replay-after-resume (tier-1 budget).
    write_stream(f, n=30_000)
    ck = tmp_path / "ck"
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    args = [sys.executable, "-m", "tpu_cooccurrence.cli", "-i", str(f),
            "-ws", "20", "-ic", "8", "-uc", "5", "-s", "0xC0FFEE",
            "--backend", "oracle", "--checkpoint-dir", str(ck),
            "--checkpoint-every-windows", "5"]

    clean = subprocess.run(args[:-4] + ["--checkpoint-dir",
                                        str(tmp_path / "ck-clean"),
                                        "--checkpoint-every-windows", "5"],
                           capture_output=True, text=True, env=env,
                           cwd=repo, timeout=300)
    assert clean.returncode == 0, clean.stderr[-800:]

    victim = subprocess.Popen(args, stdout=subprocess.PIPE,
                              stderr=subprocess.DEVNULL, env=env, cwd=repo)
    deadline = time.monotonic() + 240
    while not list(ck.glob("state.*.npz")) and time.monotonic() < deadline:
        if victim.poll() is not None:
            break
        time.sleep(0.05)
    if victim.poll() is None:
        victim.send_signal(signal.SIGKILL)
        victim.wait(timeout=60)
        assert victim.returncode == -signal.SIGKILL
    assert list(ck.glob("state.*.npz")), \
        "no checkpoint landed before the run ended"

    resumed = subprocess.run(args, capture_output=True, text=True, env=env,
                             cwd=repo, timeout=300)
    assert resumed.returncode == 0, resumed.stderr[-800:]
    assert resumed.stdout == clean.stdout


def test_bench_history_skips_corrupt_lines(tmp_path, monkeypatch):
    """bench._last_onchip must survive a truncated append (crashed run):
    corrupt lines are skipped, the last good record wins."""
    import importlib.util
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "bench_mod", os.path.join(repo, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    hist = tmp_path / "hist.jsonl"
    hist.write_text('{"ts": "t1", "pairs_per_sec": 1.0, "vs_baseline": 1}\n'
                    '{"ts": "t2", "pairs_per_sec": 2.0, "vs_ba')
    monkeypatch.setattr(bench, "_HISTORY", str(hist))
    assert bench._last_onchip()["ts"] == "t1"
    hist.write_text("not json at all\n")
    assert bench._last_onchip() is None
