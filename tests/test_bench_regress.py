"""bench.regress: the bench-history regression gate (median +/- MAD
noise bands per metric per backend; exit 1 on regression, 0 on a clean
or too-thin history)."""

import json
import os

from tpu_cooccurrence.bench import regress

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _entry(pairs=1000.0, backend="numpy", **over):
    e = {"backend": backend, "pairs_per_sec": pairs,
         "serving": {"qps": 500.0, "query_p99_s": 0.004},
         "ts": "2026-08-01T00:00:00"}
    e.update(over)
    return e


def _history(n=5, pairs=1000.0, backend="numpy",
             jitter=(0.98, 1.0, 1.02, 0.99, 1.01)):
    return [_entry(pairs=pairs * jitter[i % len(jitter)],
                   backend=backend)
            for i in range(n)]


def test_flatten_skips_verdict_and_bools():
    flat = regress.flatten(_entry(
        ok=True, regression={"ok": False, "regressions": [{"x": 1}]},
        note="text", nested={"deep": {"v": 2.0}, "flag": False}))
    assert flat["pairs_per_sec"] == 1000.0
    assert flat["serving.qps"] == 500.0
    assert flat["nested.deep.v"] == 2.0
    assert not any(k.startswith("regression") for k in flat)
    assert "ok" not in flat and "nested.flag" not in flat
    assert "ts" not in flat and "note" not in flat


def test_regression_flagged_on_2x_throughput_drop():
    verdict = regress.evaluate(_history(), _entry(pairs=500.0))
    assert not verdict["ok"]
    metrics = {r["metric"] for r in verdict["regressions"]}
    assert "pairs_per_sec" in metrics
    reg = next(r for r in verdict["regressions"]
               if r["metric"] == "pairs_per_sec")
    assert reg["direction"] == "higher" and reg["n_history"] == 5


def test_within_band_and_improvement_pass():
    assert regress.evaluate(_history(), _entry(pairs=990.0))["ok"]
    # A 2x IMPROVEMENT is news, not a regression.
    assert regress.evaluate(_history(), _entry(pairs=2000.0))["ok"]


def test_lower_is_better_metrics_flag_rises():
    hist = _history()
    worse = _entry(serving={"qps": 500.0, "query_p99_s": 0.05})
    verdict = regress.evaluate(hist, worse)
    assert not verdict["ok"]
    assert {r["metric"] for r in verdict["regressions"]} == \
        {"serving.query_p99_s"}
    better = _entry(serving={"qps": 500.0, "query_p99_s": 0.0001})
    assert regress.evaluate(hist, better)["ok"]


def test_backends_never_cross_band():
    """CPU-fallback history must not band a TPU candidate (and vice
    versa) — a backend switch is not a regression."""
    hist = _history(backend="numpy")
    verdict = regress.evaluate(hist, _entry(pairs=10.0, backend="jax"))
    assert verdict["ok"] and verdict["checked"] == 0
    assert "pairs_per_sec" in verdict["insufficient_history"]


def test_thin_history_passes_gate():
    verdict = regress.evaluate(_history(n=2), _entry(pairs=1.0))
    assert verdict["ok"] and verdict["checked"] == 0
    assert "pairs_per_sec" in verdict["insufficient_history"]


def test_quiet_history_uses_relative_floor():
    """MAD ~ 0 (identical runs) must not flag ordinary jitter — the
    relative floor keeps the band at rel_floor * median."""
    hist = [_entry(pairs=1000.0) for _ in range(5)]
    assert regress.evaluate(hist, _entry(pairs=950.0))["ok"]
    assert not regress.evaluate(hist, _entry(pairs=850.0))["ok"]


def test_cli_exit_codes(tmp_path, capsys):
    hpath = tmp_path / "hist.jsonl"
    with open(hpath, "w") as f:
        for e in _history():
            f.write(json.dumps(e) + "\n")
        f.write("{torn line\n")  # tolerated, skipped
    # Newest-entry mode: append a 2x regression as the candidate.
    with open(hpath, "a") as f:
        f.write(json.dumps(_entry(pairs=480.0)) + "\n")
    assert regress.main(["--history", str(hpath)]) == 1
    assert "REGRESSION pairs_per_sec" in capsys.readouterr().out
    # Explicit candidate file (bench.py stdout shape: "value" headline).
    cand = tmp_path / "cand.json"
    cand.write_text(json.dumps({"backend": "numpy", "value": 995.0}))
    assert regress.main(["--history", str(hpath), "--candidate",
                         str(cand), "--format", "json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["ok"] and out["checked"] >= 1
    # Empty/missing history: nothing to band, gate stays open.
    assert regress.main(["--history", str(tmp_path / "nope.jsonl")]) == 0


def test_gate_passes_on_repo_history():
    """The checked-in bench_history.jsonl must pass its own gate — the
    verify skill runs exactly this command after the bench step."""
    path = os.path.join(REPO, "bench_history.jsonl")
    assert regress.main(["--history", path]) == 0
