"""Pipelined execution (`--pipeline-depth`, pipeline.py) tests.

The contract under test is EXACT parity: at any depth the pipelined job
must emit bit-identical per-window top-K tables, final results, and
counters to the serial path on the same seeded Zipfian stream — the
overlap is a scheduling change, not a math change. Plus the lifecycle
guarantees: ordered mid-stream shutdown (nothing dropped or
double-applied), worker-failure latching (no deadlocked producer), and
the checkpoint barrier.
"""

import numpy as np
import pytest

from tpu_cooccurrence.config import Backend, Config
from tpu_cooccurrence.io.synthetic import zipfian_interactions
from tpu_cooccurrence.job import CooccurrenceJob
from tpu_cooccurrence.pipeline import PipelineDriver, PipelineError, StagedWindow
from tpu_cooccurrence.state.results import materialize_dense


def zipf_stream(n=12_000, n_items=400, n_users=150, seed=3):
    return zipfian_interactions(n, n_items=n_items, n_users=n_users,
                                alpha=1.1, seed=seed, events_per_ms=40)


def run_job(backend, depth, users, items, ts, chunk=997, collect=False,
            **cfg_kw):
    cfg_kw.setdefault("item_cut", 50)
    cfg_kw.setdefault("user_cut", 50)
    cfg = Config(window_size=100, seed=7,
                 backend=Backend(backend), pipeline_depth=depth, **cfg_kw)
    job = CooccurrenceJob(cfg)
    emitted = []
    if collect:
        # Per-window emission stream: in pipelined mode this fires on the
        # scorer worker, in serial mode on the caller — the sequences
        # must still be identical (FIFO scoring order).
        job.on_update = lambda out: emitted.append(materialize_dense(out))
    for lo in range(0, len(users), chunk):
        job.add_batch(users[lo:lo + chunk], items[lo:lo + chunk],
                      ts[lo:lo + chunk])
    job.finish()
    return job, emitted


def assert_jobs_identical(a, b):
    assert a.counters.as_dict() == b.counters.as_dict()
    assert a.windows_fired == b.windows_fired
    assert set(a.latest) == set(b.latest)
    for item in a.latest:
        assert a.latest[item] == b.latest[item], item


# -- exact serial-vs-pipelined parity ----------------------------------


@pytest.mark.parametrize("backend", ["oracle", "sparse", "device"])
@pytest.mark.parametrize("depth", [1, 2])
def test_parity_final_state(backend, depth):
    """Final top-K tables and every counter are bit-identical to serial."""
    users, items, ts = zipf_stream()
    serial, _ = run_job(backend, 0, users, items, ts)
    piped, _ = run_job(backend, depth, users, items, ts)
    assert_jobs_identical(serial, piped)


@pytest.mark.parametrize("backend", ["oracle", "sparse"])
def test_parity_every_window(backend):
    """The per-window emission stream matches window for window.

    --emit-updates keeps per-window results flowing (no deferred table),
    so this pins the FIFO ordering guarantee: window N's table is
    identical AND arrives before window N+1's, exactly as in serial.
    """
    users, items, ts = zipf_stream()
    _, serial_windows = run_job(backend, 0, users, items, ts,
                                collect=True, emit_updates=True)
    _, piped_windows = run_job(backend, 2, users, items, ts,
                               collect=True, emit_updates=True)
    assert len(serial_windows) == len(piped_windows)
    assert serial_windows == piped_windows


def test_parity_sliding_windows():
    """Sliding mode (stateless sampler, no feedback edge) pipelines too."""
    users, items, ts = zipf_stream(n=8_000)
    serial, _ = run_job("oracle", 0, users, items, ts, window_slide=50)
    piped, _ = run_job("oracle", 2, users, items, ts, window_slide=50)
    assert_jobs_identical(serial, piped)


def test_parity_with_feedback_edge():
    """Aggressive cuts produce rejections; the feedback decrement stays on
    the sampling thread and must land before the NEXT window fires —
    divergence here would show up as different sampled pair counts."""
    users, items, ts = zipf_stream()
    serial, _ = run_job("oracle", 0, users, items, ts, item_cut=8,
                        user_cut=4)
    piped, _ = run_job("oracle", 2, users, items, ts, item_cut=8,
                       user_cut=4)
    assert_jobs_identical(serial, piped)


def test_parity_across_checkpoint_barrier(tmp_path):
    """Periodic checkpoints barrier the pipeline; the snapshot point (and
    everything after it) matches serial exactly."""
    users, items, ts = zipf_stream(n=8_000)
    serial, _ = run_job("sparse", 0, users, items, ts,
                        checkpoint_dir=str(tmp_path / "s"),
                        checkpoint_every_windows=2)
    piped, _ = run_job("sparse", 2, users, items, ts,
                       checkpoint_dir=str(tmp_path / "p"),
                       checkpoint_every_windows=2)
    assert_jobs_identical(serial, piped)
    assert piped.pipeline is not None
    assert piped.pipeline.windows_processed == piped.windows_fired


# -- lifecycle: shutdown, drain, failure -------------------------------


def test_mid_stream_close_drops_nothing():
    """Killing the driver mid-stream processes everything already
    submitted exactly once; resuming afterwards still ends bit-identical
    to serial (nothing dropped, nothing double-applied)."""
    users, items, ts = zipf_stream()
    serial, _ = run_job("oracle", 0, users, items, ts)

    cfg = Config(window_size=100, seed=7, item_cut=50, user_cut=50,
                 backend=Backend.ORACLE, pipeline_depth=2)
    job = CooccurrenceJob(cfg)
    half = len(users) // 2
    job.add_batch(users[:half], items[:half], ts[:half])
    fired_at_close = job.windows_fired
    job.pipeline.close()  # ordered: drains the queue, then joins
    # Every submitted window was scored exactly once before the join.
    assert job.pipeline.windows_processed == fired_at_close
    assert len(job.step_timer.windows) == fired_at_close
    # The driver restarts its worker on the next submit; the stream
    # continues and the end state is still exact.
    job.add_batch(users[half:], items[half:], ts[half:])
    job.finish()
    assert job.pipeline.windows_processed == job.windows_fired
    assert_jobs_identical(serial, job)


def test_worker_failure_latches_and_raises():
    """A scorer failure on the worker re-raises on the caller thread as
    PipelineError, and the producer can never deadlock against the dead
    consumer (queued slots keep being recycled)."""

    class ExplodingScorer:
        accepts_aggregated = False

        def process_window(self, ts, pairs):
            raise RuntimeError("boom")

    cfg = Config(window_size=100, seed=7, backend=Backend.ORACLE,
                 pipeline_depth=1)
    job = CooccurrenceJob(cfg, scorer=ExplodingScorer())
    users, items, ts = zipf_stream(n=4_000)
    with pytest.raises(PipelineError, match="boom"):
        for lo in range(0, len(users), 499):
            job.add_batch(users[lo:lo + 499], items[lo:lo + 499],
                          ts[lo:lo + 499])
        job.finish()
    # The raise tears the worker down first: a caller that catches the
    # error and discards the job must not leak a parked daemon thread
    # (which would pin the job, scorer, and device buffers forever).
    worker = job.pipeline._worker
    assert worker is None or not worker.is_alive()


def test_submit_order_is_fifo():
    """Windows are scored in exactly the submitted order (the parity
    guarantee's mechanical core), even at depth 2."""

    class Recorder:
        accepts_aggregated = False

        def __init__(self):
            self.seen = []

        def process_window(self, ts, pairs):
            self.seen.append(ts)
            return []

    cfg = Config(window_size=100, seed=7, backend=Backend.ORACLE,
                 pipeline_depth=2)
    rec = Recorder()
    job = CooccurrenceJob(cfg, scorer=rec)
    driver = job.pipeline
    for w in range(7):
        driver.submit(StagedWindow(ts=w, payload=None, events=0,
                                   raw_pairs=0, sample_seconds=0.0))
    driver.barrier()
    assert rec.seen == list(range(7))
    driver.close()


def test_staging_ring_is_bounded():
    """Backpressure: the ring never allocates beyond depth + 1 slots, and
    every slot is recycled by the end of the run."""
    users, items, ts = zipf_stream(n=8_000)
    job, _ = run_job("sparse", 2, users, items, ts)
    ring = job.pipeline.ring
    assert ring._free.qsize() == 2 + 1  # queue positions + active side


# -- configuration surface ---------------------------------------------


def test_depth_validation():
    # Config validates in __post_init__ — construction itself raises.
    with pytest.raises(ValueError, match="pipeline-depth"):
        Config(window_size=100, pipeline_depth=3)
    # Multi-host pipelining is supported: every collective issues from one
    # thread in window order, so a coordinator plus depth > 0 is valid.
    Config(window_size=100, pipeline_depth=1, coordinator="h:1234",
           num_processes=2, process_id=0, backend=Backend.SHARDED,
           num_shards=2, num_items=64)
    # ... except with the partitioned sampler, whose sampling-thread
    # allgather would race the scorer worker's collectives.
    with pytest.raises(ValueError, match="partition-sampling"):
        Config(window_size=100, pipeline_depth=1, partition_sampling=True,
               coordinator="h:1234", num_processes=2, process_id=0,
               backend=Backend.SHARDED, num_shards=2, num_items=64)
    # Multi-host --degrade needs the serial path: the per-window shed vote
    # is only in lockstep with sampling at depth 0.
    with pytest.raises(ValueError, match="pipeline-depth 0"):
        Config(window_size=100, pipeline_depth=1, degrade=True,
               coordinator="h:1234", num_processes=2, process_id=0,
               backend=Backend.SHARDED, num_shards=2, num_items=64)
    with pytest.raises(ValueError):
        PipelineDriver(job=None, depth=0)


def test_occupancy_reports_both_stages():
    """StepTimer.occupancy feeds the run log and bench JSON; both stage
    fractions and the wall clock must be present and sane."""
    users, items, ts = zipf_stream(n=6_000)
    job, _ = run_job("oracle", 1, users, items, ts)
    occ = job.step_timer.occupancy(1.0)
    assert set(occ) == {"host_busy_pct", "score_busy_pct", "wall_seconds"}
    assert occ["host_busy_pct"] > 0
    assert occ["score_busy_pct"] > 0
