"""Cross-surface soak: the combinations no single-feature test crosses.

Each case drives the REAL CLI end to end on a moderately large stream
and holds the framework's strongest property — byte-identical stdout —
across feature products that interact through independent subsystems:
sparse slab state x sliding windows x per-window emission x periodic
checkpoints x a SIGKILL mid-run under the auto-resume supervisor
(reference analogues: sliding window math it never wires,
checkpointing it leaves off, Flink restart strategies — SURVEY §5,7).
"""

import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ENV = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")


def _write_soak_stream(path, n=30_000, seed=0x50A):
    """Bursty stream with duplicates and mild ts jitter (late events)."""
    rng = np.random.default_rng(seed)
    users = rng.integers(0, 300, n)
    items = rng.zipf(1.3, n).clip(1, 5_000) + 99
    ts = np.cumsum(rng.integers(0, 4, n))
    jitter = rng.integers(0, 8, n)
    ts = ts - jitter * (rng.random(n) < 0.05)  # ~5% late arrivals
    with open(path, "w") as f:
        for u, i, t in zip(users, items, ts):
            f.write(f"{u},{i},{int(t)}\n")


def _run(args, timeout=600):
    r = subprocess.run([sys.executable, "-m", "tpu_cooccurrence.cli"]
                       + args, capture_output=True, text=True, env=ENV,
                       cwd=REPO, timeout=timeout)
    assert r.returncode == 0, r.stderr[-1500:]
    return r.stdout


def _fold_updates(out: str) -> dict:
    """Collapse an --emit-updates stream to its final state: each line
    replaces that item's row, so the last occurrence per item wins."""
    state = {}
    for line in out.splitlines():
        item, rest = line.split("\t")
        state[int(item)] = rest
    return state


@pytest.mark.slow
@pytest.mark.parametrize("backend,extra", [
    ("sparse", ["--emit-updates"]),
    ("sparse", []),              # deferred results + fixed-shape auto
    ("oracle", ["--emit-updates"]),
    ("oracle", []),
])
def test_sliding_sparse_sigkill_supervised_recovery(tmp_path, backend,
                                                    extra):
    """SIGKILL right after the first periodic checkpoint, under the
    supervisor, on a sliding-window cut stream. Final-dump mode must be
    BYTE-identical to an uninterrupted run; --emit-updates mode must be
    complete-and-equivalent (the resumed child replays restored rows
    once as current state rather than re-emitting each pre-crash
    window's historical updates — supervisor.py's documented contract),
    so the streams' folded final states must match exactly."""
    f = tmp_path / "in.csv"
    _write_soak_stream(f)
    base = ["-i", str(f), "-ws", "400", "--window-slide", "100",
            "-ic", "20", "-uc", "8", "-s", "0xC0FFEE",
            "--backend", backend,
            "--checkpoint-every-windows", "25"] + extra

    clean = _run(base + ["--checkpoint-dir", str(tmp_path / "ck-clean")])
    assert clean, "soak stream produced no output"

    from tpu_cooccurrence.supervisor import supervise

    class _Sink:
        text = ""

        def write(self, s):
            self.text += s

    ck = tmp_path / "ck"
    worker = os.path.join(REPO, "tests", "supervised_crash_worker.py")
    marker = tmp_path / "crashed-once"
    # supervise() respawns the worker; the worker arms its SIGKILL
    # watcher only on the first attempt (marker file). The child
    # inherits the conftest's forced-CPU env.
    sink = _Sink()
    rc = supervise([sys.executable, worker, str(ck), str(marker)] + base
                   + ["--checkpoint-dir", str(ck)],
                   attempts=2, delay_s=0, stdout=sink)
    assert rc == 0
    assert marker.exists(), "crash never injected"
    if "--emit-updates" in extra:
        assert _fold_updates(sink.text) == _fold_updates(clean), (
            "recovered stream's final state diverges from the clean run")
    else:
        assert sink.text == clean, "recovered stdout diverges from clean run"


@pytest.mark.slow
def test_backend_cross_agreement_on_soak_stream(tmp_path):
    """All four execution modes (oracle, device, sparse, sharded-sparse
    x8) agree item-for-item on the soak stream at display precision."""
    f = tmp_path / "in.csv"
    _write_soak_stream(f)
    base = ["-i", str(f), "-ws", "400", "-ic", "20", "-uc", "8",
            "-s", "0xC0FFEE"]
    outs = {
        "oracle": _run(base + ["--backend", "oracle"]),
        "device": _run(base + ["--backend", "device"]),
        "sparse": _run(base + ["--backend", "sparse"]),
        "sharded-sparse": _run(base + ["--backend", "sparse",
                                       "--num-shards", "8"]),
    }

    def parse(out):
        res = {}
        for line in out.splitlines():
            item, rest = line.split("\t")
            res[int(item)] = [(int(p.rsplit(":", 1)[0]),
                               float(p.rsplit(":", 1)[1]))
                              for p in rest.split()]
        return res

    from test_pipeline import assert_latest_close

    ref = parse(outs["oracle"])
    assert ref
    for name in ("device", "sparse", "sharded-sparse"):
        # The shared f32-vs-f64 protocol: scores to tolerance, ids exact
        # only where in-row score gaps beat it (near-ties legitimately
        # reorder across precisions/backends).
        assert_latest_close(ref, parse(outs[name]), atol=2e-3)
