"""Top-K heap oracle tests, mirroring the reference's heap tests
(``IntDoublePriorityQueueTest.java``)."""

import numpy as np

from tpu_cooccurrence.oracle.heap import TopKHeap


def test_add_ascending_order():
    q = TopKHeap(10)
    for i in range(10):
        q.add(i, float(i))
    assert q.least_value() == 0
    assert q.least_score() == 0.0


def test_add_descending_order():
    q = TopKHeap(10)
    for i in reversed(range(10)):
        q.add(i, float(i))
    assert q.least_value() == 0
    assert q.least_score() == 0.0


def test_random_elements_against_sort_oracle():
    # Reference: IntDoublePriorityQueueTest.java:37-75 (seed 0xC0FFEE).
    rng = np.random.default_rng(0xC0FFEE)
    n, k = 100, 10
    scores = rng.random(n)
    q = TopKHeap(k)
    for i in range(n):
        q.offer(i, float(scores[i]))
    srt = np.sort(scores)
    assert q.least_score() == srt[n - k]
    top = sorted(s for _, s in q)
    np.testing.assert_array_equal(top, srt[n - k:])


def test_reset_and_reuse():
    q = TopKHeap(10)
    for i in range(3):
        q.add(i, float(i))
    assert q.size == 3
    q.reset()
    for i in range(10):
        q.add(i, float(i))
    assert q.size == 10
    assert q.least_value() == 0
    assert q.least_score() == 0.0


def test_tie_keeps_earlier_insert():
    # offer() replaces the min only on strictly greater score
    # (ItemRowRescorerTwoInputStreamOperator.java:220).
    q = TopKHeap(2)
    q.offer(1, 5.0)
    q.offer(2, 5.0)
    q.offer(3, 5.0)  # tie with current min: must NOT displace
    values = {v for v, _ in q}
    assert values == {1, 2}


def test_sorted_desc():
    q = TopKHeap(3)
    for v, s in [(7, 1.0), (8, 3.0), (9, 2.0)]:
        q.offer(v, s)
    assert q.sorted_desc() == [(8, 3.0), (9, 2.0), (7, 1.0)]
